"""DirtyScheduler: the change-driven recompute loop (SURVEY.md §2 #8, §3 #2).

Tick protocol (tick-synchronous, batched — SURVEY.md §0):

1. ``push`` buffers deltas at sources (host boundary in).
2. ``tick()`` drains the buffers, computes the structural dirty frontier
   (nodes reachable from dirty sources, in topo order — no device values are
   consulted), and hands the plan to the executor.
3. Deltas arriving on back-edges re-enter at loop nodes; the scheduler
   re-runs the (restricted) plan until quiescence or ``max_loop_iters`` —
   this is the host-driven fixpoint for iterative graphs like PageRank.
4. Sink deltas are folded into materialized host views (host boundary out).

The scheduler is deliberately cheap, host-side Python: all heavy lifting is
in the executor.
"""

from __future__ import annotations

import dataclasses
import time
from collections import Counter, defaultdict
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.executors import CpuExecutor, Executor
from reflow_tpu.utils.config import env_float
from reflow_tpu.graph import FlowGraph, GraphError, Node
from reflow_tpu.obs import trace as _trace

__all__ = ["DirtyScheduler", "TickResult"]


class LazyScalar:
    """Deferred sum of host ints and device scalars.

    Composing tick metadata (``1 + iters``, ``deltas_in + loop_rows``)
    with eager jnp arithmetic would dispatch a device op per tick — and
    on a tunnel-attached runtime every execution carries a large fixed
    overhead, with scalar-chained ops the worst case. This keeps the
    parts un-combined until ``int()`` forces them at the sync point."""

    __slots__ = ("parts",)

    def __init__(self, *parts):
        self.parts = parts

    def __int__(self) -> int:
        def force(p):
            if isinstance(p, int):
                return p
            if callable(p):
                return int(p())
            return int(np.asarray(p).sum())

        return sum(force(p) for p in self.parts)

    def __bool__(self) -> bool:
        return int(self) != 0

    def __add__(self, other):
        return LazyScalar(*self.parts, other)

    __radd__ = __add__


def lazy_add(a, b):
    """a + b without an eager device op when either side is device-resident."""
    if isinstance(a, int) and isinstance(b, int):
        return a + b
    return LazyScalar(a, b)


def _count_nonzero_global(w) -> int:
    """Live-row count of a weights column that may be a MULTI-HOST global
    array (process-local ingestion): np.asarray on a partially-
    addressable array is illegal, so count the addressable shards. The
    count is deliberately the PROCESS-LOCAL share on multi-controller
    runs — never a hidden cross-process collective, which would deadlock
    any non-SPMD metrics access (e.g. `if process_index() == 0:
    summarize(...)`). Sum deltas_in across processes for global totals.
    Single-controller arrays take the plain path."""
    if getattr(w, "is_fully_addressable", True):
        return int(np.count_nonzero(np.asarray(w)))
    return sum(int(np.count_nonzero(np.asarray(s.data)))
               for s in w.addressable_shards)


@dataclasses.dataclass
class TickResult:
    """Per-tick observability record (SURVEY.md §5 metrics).

    After ``tick(sync=False)`` the scalar fields may still be
    device-resident (pipelined streaming: nothing blocked on the device);
    call :meth:`block` to force them to host Python values.
    """

    tick: int
    sink_deltas: Dict[str, DeltaBatch]
    passes: int
    dirty_nodes: int
    deltas_in: int
    deltas_out: int
    wall_s: float
    quiesced: bool
    #: captured executor error check for streaming ticks whose per-tick
    #: check was deferred; ``block()`` (the documented streaming sync
    #: point) runs it so sticky flags can't finish a run unsurfaced
    #: (ADVICE r2: a pure-streaming run never otherwise checked)
    _check_errors: Optional[Callable[[], None]] = dataclasses.field(
        default=None, repr=False, compare=False)
    #: this tick forced a mid-stream device readback (synchronous tick or
    #: sink materialization on a device executor) — the tunnel-degrading
    #: event counted by MetricsSummary.forced_syncs (VERDICT r3 weak #6)
    forced_sync: bool = False

    @property
    def delta_ops(self) -> int:
        """Delta rows processed — numerator of delta-ops/sec (BASELINE.md)."""
        return self.deltas_in + self.deltas_out

    def block(self) -> "TickResult":
        """Force any device-resident scalar fields to host values and
        surface deferred executor errors (the streaming sync point; a
        no-op for synchronous ticks). Macro-tick results (tick_many)
        carry per-tick [K] stacks; they aggregate here."""
        def to_int(x):
            if isinstance(x, (int, LazyScalar)):
                return int(x)
            return int(np.asarray(x).sum())

        self.passes = to_int(self.passes)
        self.deltas_in = to_int(self.deltas_in)
        self.deltas_out = to_int(self.deltas_out)
        q = self.quiesced() if callable(self.quiesced) else self.quiesced
        self.quiesced = bool(np.asarray(q).all())
        if self._check_errors is not None:
            check, self._check_errors = self._check_errors, None
            check()
        return self


class SourceCursor:
    """Deterministic batch-id mint for exactly-once ingestion.

    Under at-least-once upstream delivery, ``push(batch_id=...)`` dedups
    replays. On MULTI-CONTROLLER runs the dedup sets must stay
    SPMD-identical across processes (checkpoint meta assumes it —
    verified collectively at save); deriving ids from a shared monotone
    cursor makes that identity true BY CONSTRUCTION: every process mints
    ``"<source>@<seq>"`` for the same global batch, regardless of which
    local rows it contributes (``shard_batch_process_local``).

    ``resume`` re-derives the cursor position after a checkpoint restore
    from the restored dedup window, so a restarted driver neither reuses
    an accepted id (its push would dedup away) nor skips one.
    """

    __slots__ = ("name", "seq")

    def __init__(self, source: Node, start: int = 0):
        self.name = source.name
        self.seq = start

    def next_id(self) -> str:
        bid = f"{self.name}@{self.seq}"
        self.seq += 1
        return bid

    @classmethod
    def resume(cls, sched: "DirtyScheduler", source: Node) -> "SourceCursor":
        prefix = source.name + "@"
        top = -1
        for bid in sched._seen_batch_ids:
            if bid.startswith(prefix):
                try:
                    top = max(top, int(bid[len(prefix):]))
                except ValueError:
                    pass
        return cls(source, top + 1)


class _StagedTicks:
    """Handle for one staged-but-undispatched fused window
    (``stage_window`` → ``dispatch_staged`` → ``retire_staged``): the
    executor's :class:`StagedWindow` plus the scheduler-side facts the
    dispatch needs to build the aggregated TickResult."""

    __slots__ = ("sw", "k", "host_rows", "plan")

    def __init__(self, sw, k: int, host_rows: int, plan):
        self.sw = sw
        self.k = k
        self.host_rows = host_rows
        self.plan = plan


class DirtyScheduler:
    def __init__(self, graph: FlowGraph, executor: Optional[Executor] = None,
                 *, max_loop_iters: int = 10_000,
                 dedup_window: int = 1 << 20):
        graph.validate()
        self.graph = graph
        self.executor = executor if executor is not None else CpuExecutor()
        self.executor.bind(graph)
        self.max_loop_iters = max_loop_iters
        self._pending: Dict[int, List[DeltaBatch]] = defaultdict(list)
        #: insertion-ordered dedup set for idempotent pushes, bounded to
        #: the newest ``dedup_window`` ids (upstream redelivery must stay
        #: within that horizon)
        self._seen_batch_ids: Dict[str, None] = {}
        self._metric_keys: list = []  # (registry, key) published
        self.dedup_window = dedup_window
        self._tick = 0
        self.sink_views: Dict[str, Counter] = {s.name: Counter() for s in graph.sinks}
        self.history: List[TickResult] = []
        #: mid-stream device readbacks this scheduler forced (sync ticks,
        #: sink materialization, read_table on a device executor). On a
        #: tunnel runtime the FIRST of these permanently degrades
        #: dispatch, so the first increments also emits a one-time
        #: warning (utils/runtime.note_forced_sync) — VERDICT r3 weak #6
        self.forced_syncs = 0
        #: mega-tick window path (docs/guide.md "Compiled mega-ticks"):
        #: windows dispatched through the device ingress queue vs windows
        #: that fell back (ragged feeds too wasteful, over-capacity
        #: batches, device-resident feeds, unsupported graph)
        self.megatick_windows = 0
        self.megatick_fallbacks = 0
        #: max tolerated padding waste: the fraction of the window's
        #: (tick, source) slots that would be zero-row padding. Divergent
        #: per-tick dirty sets above this run the per-tick path instead
        self.megatick_waste = env_float("REFLOW_MEGATICK_WASTE")

    # -- host boundary in --------------------------------------------------

    def push(self, source: Node, batch: DeltaBatch, *,
             batch_id: Optional[str] = None) -> bool:
        """Buffer deltas at a source — or at a loop variable, which is how a
        fixpoint computation receives its initial condition.

        ``batch_id`` makes ingestion idempotent (exactly-once under
        at-least-once upstream delivery, SURVEY.md §5): a batch whose id
        was already accepted — including before a checkpoint/restore — is
        dropped. Returns whether the batch was accepted.
        """
        if source.kind not in ("source", "loop"):
            raise GraphError(f"can only push to sources/loops, not {source}")
        if batch_id is not None and not self._register_batch_id(batch_id):
            return False
        # device-resident batches are enqueued unconditionally: their
        # len() is a device->host readback (DeviceDelta.__len__), and any
        # readback permanently degrades a tunnel-attached runtime's
        # pipelining — a padded all-zero-weight batch is a cheap no-op
        if not hasattr(batch, "nonzero") and not len(batch):
            return True
        self._pending[source.id].append(batch)
        return True

    def _register_batch_id(self, batch_id: str) -> bool:
        """Record ``batch_id`` in the bounded dedup window. Returns False
        (without touching the window) when the id is already held — a
        replay inside the horizon. Eviction is pure insertion order: a
        rejected replay does NOT refresh its id's position, so the
        horizon is "newest ``dedup_window`` *accepted* ids"."""
        if batch_id in self._seen_batch_ids:
            return False
        self._seen_batch_ids[batch_id] = None
        while len(self._seen_batch_ids) > self.dedup_window:
            self._seen_batch_ids.pop(next(iter(self._seen_batch_ids)))
        return True

    # -- dirty planning (structural) --------------------------------------

    def _dirty_plan(self, dirty_roots: Sequence[int]) -> List[Node]:
        dirty = set(dirty_roots)
        plan = []
        for node in self.graph.nodes:  # construction order == topo order
            if node.id in dirty:
                plan.append(node)
                continue
            if node.kind in ("source", "loop"):
                continue
            if any(i.id in dirty for i in node.inputs):
                dirty.add(node.id)
                plan.append(node)
        return plan

    # -- the tick ----------------------------------------------------------

    def tick(self, *, sync: bool = True) -> TickResult:
        """Run one tick. ``sync=False`` (streaming mode) skips the
        per-tick device readback for iterative graphs fully fused on
        device: ticks enqueue back-to-back and the returned TickResult's
        scalars stay device-resident until ``block()``. Graphs with sinks
        or host-driven loops still materialize synchronously."""
        t0 = time.perf_counter()

        def _merge_pending(batches):
            # a device-resident batch passes through untouched (host
            # concat would force readbacks); it cannot be merged with
            # other same-tick batches for the same source
            if any(hasattr(b, "nonzero") for b in batches):
                if len(batches) > 1:
                    raise GraphError(
                        "a device-resident batch cannot be merged with "
                        "other pending batches for the same source in "
                        "one tick; push it alone")
                return batches[0]
            return DeltaBatch.concat(batches)

        ingress: Dict[int, DeltaBatch] = {
            nid: _merge_pending(batches)
            for nid, batches in self._pending.items()
        }
        self._pending.clear()
        # device batches defer their live-row count entirely (len() or an
        # eager nonzero() would read back / dispatch mid-tick);
        # TickResult.block() counts them at the sync point
        deltas_in = sum(len(b) for b in ingress.values()
                        if not hasattr(b, "nonzero"))
        dev_counts = [
            (lambda w=b.weights: _count_nonzero_global(w))
            for b in ingress.values() if hasattr(b, "nonzero")]
        if dev_counts:
            deltas_in = LazyScalar(deltas_in, *dev_counts)
        deltas_out = 0
        passes = 0
        dirty_union: set = set()
        sink_deltas: Dict[str, List[DeltaBatch]] = defaultdict(list)
        quiesced = True
        sink_ids = {s.id: s for s in self.graph.sinks}

        while ingress:
            if passes >= self.max_loop_iters:
                # PAUSE, don't drop: the leftover loop deltas re-enter as
                # pending for the next tick, so join/reduce state stays
                # mutually consistent and a later tick (or a repair
                # protocol like workloads/sssp.repair) resumes exactly
                # where the halted iteration stopped
                quiesced = False
                for nid, batch in ingress.items():
                    self._pending[nid].append(batch)
                break
            plan = self._dirty_plan(list(ingress))
            dirty_union.update(n.id for n in plan)
            if passes == 0 and self.graph.loops:
                # iterative graph: let the executor fuse the entire tick
                # (all fixpoint passes) into one on-device program
                fx = self.executor.run_tick_fixpoint(
                    plan, ingress, self.max_loop_iters, sync=sync)
                if fx is not None:
                    (sink_batches, fx_passes, loop_rows, quiesced,
                     extra_dirty, leftover) = fx
                    passes = fx_passes
                    deltas_in = lazy_add(deltas_in, loop_rows)
                    dirty_union.update(extra_dirty)
                    for sid, batches in sink_batches.items():
                        sink_deltas[sink_ids[sid].name].extend(batches)
                    # a max_iters halt pauses: live carry re-enters as
                    # pending so the next tick resumes the iteration
                    for nid, b in leftover.items():
                        self._pending[nid].append(b)
                    break
            egress = self.executor.run_pass(plan, ingress)
            passes += 1
            ingress = {}
            for nid, batch in egress.items():
                if nid in sink_ids:
                    if len(batch):
                        sink_deltas[sink_ids[nid].name].append(batch)
                elif len(batch):  # loop back-edge -> next pass
                    ingress[nid] = batch
                    deltas_in += len(batch)

        # fail loudly if any op state carries a sticky error flag (e.g. a
        # retraction exhausted a min/max candidate buffer) BEFORE corrupt
        # deltas are folded into the materialized sink views. Streaming
        # ticks (sync=False) defer the check to the next sync point —
        # unless sink views are about to be materialized, which forces a
        # sync anyway and must not fold corrupt deltas
        checked = sync or bool(sink_deltas)
        if checked:
            if getattr(self.executor, "name", "") != "cpu":
                self._note_forced_sync("synchronous tick / sink "
                                       "materialization")
            self.executor.check_errors()

        out: Dict[str, DeltaBatch] = {}
        for name, batches in sink_deltas.items():
            # sink batches may still be device-resident (deferred readback:
            # the host crossing happens once per tick, not once per pass)
            merged = DeltaBatch.concat(
                [self.executor.materialize(b) for b in batches]).consolidate()
            out[name] = merged
            deltas_out += len(merged)
            view = self.sink_views[name]
            for k, v, w in merged.rows():
                view[(k, v)] += w
                if view[(k, v)] == 0:
                    del view[(k, v)]

        self._tick += 1
        result = TickResult(
            tick=self._tick,
            sink_deltas=out,
            passes=passes,
            dirty_nodes=len(dirty_union),
            deltas_in=deltas_in,
            deltas_out=deltas_out,
            wall_s=time.perf_counter() - t0,
            quiesced=quiesced,
            _check_errors=None if checked else self.executor.check_errors,
            forced_sync=checked and getattr(self.executor, "name",
                                            "") != "cpu",
        )
        if _trace.ENABLED:
            _trace.evt("tick", t0, result.wall_s,
                       args={"tick": self._tick,
                             "dirty": result.dirty_nodes})
        self.history.append(result)
        return result

    def tick_many(self, feeds: Sequence[Dict[Node, DeltaBatch]], *,
                  feed_ids: Optional[Sequence[Dict[Node, Sequence[str]]]]
                  = None) -> TickResult:
        """K consecutive streaming ticks, fused into ONE device execution
        when the executor supports it (the macro-tick; see
        ``TpuExecutor.run_tick_fixpoint_many``). ``feeds[t]`` is tick
        ``t``'s source-push set; semantics are identical to pushing and
        ticking each feed in order with ``sync=False``.

        ``feed_ids`` (parallel to ``feeds``) carries the producer batch
        ids a coalesced feed entry commits — the serving frontend merges
        several ``submit()`` micro-batches into one feed batch, and their
        ids must land in the dedup window atomically with the macro-tick
        so replays dedup exactly as ``push(batch_id=...)`` replays do.
        Ids are *recorded*, not filtered: the caller (the frontend's
        admission path) is responsible for rejecting duplicates before
        coalescing.

        Returns ONE aggregated TickResult covering all K ticks (scalar
        fields sum/all-combine at ``block()``). Falls back to the
        per-tick loop for executors/graphs without the fused path.
        Requires no pending pushes (push() + tick_many don't mix) and a
        sink-free graph on the fused path.
        """
        if any(self._pending.values()):
            raise GraphError("tick_many cannot run with pending push()ed "
                             "batches; tick() them first")
        if feed_ids is not None:
            if len(feed_ids) != len(feeds):
                raise GraphError(
                    f"feed_ids must parallel feeds "
                    f"({len(feed_ids)} != {len(feeds)})")
            for ids_map in feed_ids:
                for ids in ids_map.values():
                    for bid in ids:
                        self._register_batch_id(bid)
        feeds = [{src.id: b for src, b in f.items()} for f in feeds]
        for f in feeds:
            for nid in f:
                node = self.graph.nodes[nid]
                if node.kind not in ("source", "loop"):
                    raise GraphError(
                        f"can only feed sources/loops, not {node}")

        t0 = time.perf_counter()
        fx = None
        plan = self._dirty_plan(sorted({n for f in feeds for n in f}))
        if feeds:
            fx = self._run_window_path(plan, feeds)
        runner = getattr(self.executor, "run_tick_fixpoint_many", None)
        if fx is None and runner is not None and feeds:
            fx = runner(plan, feeds, self.max_loop_iters)
        if fx is None:
            # fallback: ordinary streaming ticks, aggregated lazily (no
            # readbacks here — everything combines at block(), keeping
            # the deferred-sync contract even on the unfused path)
            results = []
            for f in feeds:
                for nid, b in f.items():
                    self._pending[nid].append(b)
                results.append(self.tick(sync=False))
            merged_sinks: Dict[str, List[DeltaBatch]] = defaultdict(list)
            for r in results:
                for name, b in r.sink_deltas.items():
                    merged_sinks[name].append(b)
            agg = TickResult(
                tick=self._tick,
                sink_deltas={name: DeltaBatch.concat(bs)
                             for name, bs in merged_sinks.items()},
                passes=LazyScalar(*[r.passes for r in results]),
                dirty_nodes=max((r.dirty_nodes for r in results),
                                default=0),
                deltas_in=LazyScalar(*[r.deltas_in for r in results]),
                deltas_out=LazyScalar(*[r.deltas_out for r in results]),
                wall_s=time.perf_counter() - t0,
                quiesced=(lambda rs=results: all(
                    bool(np.asarray(r.quiesced).all()) for r in rs)),
                _check_errors=self.executor.check_errors,
            )
            if _trace.ENABLED:
                _trace.evt("tick_many", t0, agg.wall_s,
                           args={"ticks": len(feeds), "fused": False})
            self.history.append(agg)
            return agg

        passes_base, iters, rows, conv, extra_dirty = fx
        K = len(feeds)
        host_rows = sum(len(b) for f in feeds for b in f.values())
        plan_ids = {n.id for n in plan}
        self._tick += K
        result = TickResult(
            tick=self._tick,
            sink_deltas={},
            # per-tick [K] stacks stay device-resident; block() aggregates
            passes=LazyScalar(passes_base, iters),
            dirty_nodes=len(plan_ids | extra_dirty),
            deltas_in=LazyScalar(host_rows, rows),
            deltas_out=0,
            wall_s=time.perf_counter() - t0,
            quiesced=conv,
            _check_errors=self.executor.check_errors,
        )
        if _trace.ENABLED:
            _trace.evt("tick_many", t0, result.wall_s,
                       args={"ticks": K, "fused": True})
        self.history.append(result)
        return result

    # -- mega-tick window path (docs/guide.md "Compiled mega-ticks") -------

    @property
    def window_support(self) -> bool:
        """Whether the executor advertises the fused window path for the
        bound graph (the serve frontend reads this to pick admission
        accounting and the pump's default window behavior)."""
        sup = getattr(self.executor, "supports_window", None)
        return bool(sup()) if callable(sup) else False

    def _zero_batch(self, nid: int) -> DeltaBatch:
        spec = self.graph.nodes[nid].spec
        vshape = tuple(spec.value_shape)
        return DeltaBatch(np.zeros(0, np.int64),
                          np.zeros((0,) + vshape, spec.value_dtype),
                          np.zeros(0, np.int64))

    def _run_window_path(self, plan, feeds):
        """Try the device-resident window executor on this tick_many
        call: pad ragged per-tick feeds to the window's union source set
        with zero-row deltas (weight-0 rows are semantic no-ops, so the
        compiled body keeps ONE fixed plan for the whole window) and
        hand the window to ``executor.run_window``. Returns the fused
        result tuple or None — padding waste above ``megatick_waste``,
        over-capacity batches, and executor refusals fall back to the
        stacked/per-tick paths, counted in ``megatick_fallbacks``.
        Device-resident batches skip silently (they ride their own feed
        slot by design — that's the walpipe protocol, not a fallback).
        """
        run = getattr(self.executor, "run_window", None)
        if run is None or not self.window_support:
            return None
        for f in feeds:
            for b in f.values():
                if hasattr(b, "nonzero"):
                    return None
        K = len(feeds)
        union = sorted({n for f in feeds for n in f})
        if not union:
            return None
        pad_slots = sum(1 for f in feeds for nid in union
                        if nid not in f or len(f[nid]) == 0)
        if pad_slots / (K * len(union)) > self.megatick_waste:
            # dirty sets diverge too much: padding every tick to the
            # union would mostly move zeros — per-tick plans win
            self.megatick_fallbacks += 1
            return None
        padded = [dict(f) for f in feeds]
        for f in padded:
            for nid in union:
                if nid not in f:
                    f[nid] = self._zero_batch(nid)
        fx = run(plan, padded, self.max_loop_iters)
        if fx is None:
            self.megatick_fallbacks += 1
        else:
            self.megatick_windows += 1
        return fx

    # -- staged (pipelined) window path ------------------------------------
    #
    # The serve pump's software-pipelined drive of the same mega-tick:
    # stage_window (host slot writes + WAL append) can overlap a previous
    # window's device execution; dispatch_staged commits the tick horizon
    # and returns the TickResult; retire_staged re-adopts the donated
    # buffers off the critical path. stage → dispatch → retire on one
    # window is semantically identical to tick_many's fused branch.

    def stage_window(self, feeds: Sequence[Dict[Node, DeltaBatch]], *,
                     feed_ids: Optional[Sequence[Dict[Node, Sequence[str]]]]
                     = None):
        """Stage (but do not dispatch) one K-tick fused window: validate
        and pad the feeds exactly as ``tick_many``'s window path does,
        slot-write them into the executor's ingress queue, and seal the
        staged generation. Returns an opaque handle for
        :meth:`dispatch_staged` / :meth:`retire_staged`, or None when the
        window doesn't fit the fused path — the caller then falls back to
        :meth:`tick_many`, which re-checks and counts the fallback itself
        (nothing is counted or logged here on refusal, so the fallback
        isn't double-counted).

        A successful stage has already WAL-logged the window's pushes
        (append-before-dispatch, same order as ``tick_many``) and
        registered its batch ids, so the caller MUST follow with
        ``dispatch_staged`` — abandoning a staged window is a crash, not
        a fallback."""
        if any(self._pending.values()):
            raise GraphError("stage_window cannot run with pending "
                             "push()ed batches; tick() them first")
        stage = getattr(self.executor, "stage_window", None)
        if stage is None or not self.window_support or not feeds:
            return None
        nfeeds = []
        for f in feeds:
            entry = {}
            for src, b in f.items():
                if src.kind not in ("source", "loop"):
                    raise GraphError(
                        f"can only feed sources/loops, not {src}")
                if hasattr(b, "nonzero"):
                    return None  # device-resident: walpipe's own slot
                entry[src.id] = b
            nfeeds.append(entry)
        K = len(nfeeds)
        union = sorted({n for f in nfeeds for n in f})
        if not union:
            return None
        pad_slots = sum(1 for f in nfeeds for nid in union
                        if nid not in f or len(f[nid]) == 0)
        if pad_slots / (K * len(union)) > self.megatick_waste:
            return None
        plan = self._dirty_plan(union)
        padded = [dict(f) for f in nfeeds]
        for f in padded:
            for nid in union:
                if nid not in f:
                    f[nid] = self._zero_batch(nid)
        sw = stage(plan, padded, self.max_loop_iters)
        if sw is None:
            return None
        # the stage is committed: register ids and WAL-log the pushes NOW
        # (append-before-dispatch). On the earlier refusals above nothing
        # was registered, so the tick_many fallback re-registers cleanly
        # (_register_batch_id tolerates replays).
        if feed_ids is not None:
            if len(feed_ids) != len(feeds):
                raise GraphError(
                    f"feed_ids must parallel feeds "
                    f"({len(feed_ids)} != {len(feeds)})")
            for ids_map in feed_ids:
                for ids in ids_map.values():
                    for bid in ids:
                        self._register_batch_id(bid)
        self._log_window_feeds(feeds, feed_ids)
        host_rows = sum(len(b) for f in nfeeds for b in f.values())
        return _StagedTicks(sw, K, host_rows, plan)

    def _log_window_feeds(self, feeds, feed_ids) -> None:
        """Durability hook for a successfully staged window: the base
        scheduler has no log; ``DurableScheduler`` appends the window's
        push records here (append-before-dispatch)."""

    def dispatch_staged(self, handle: "_StagedTicks") -> TickResult:
        """Dispatch a staged window: ONE device execution, the tick
        horizon advances by K, and the aggregated TickResult (identical
        to ``tick_many``'s fused branch) is returned. The dispatch is
        async — the caller can stage the next window immediately and
        ``retire_staged`` this one later."""
        t0 = time.perf_counter()
        fx = self.executor.dispatch_window(handle.sw)
        if fx is None:
            # stage_window guaranteed the fused program exists — a None
            # here is a lifecycle bug, and the window's WAL records are
            # already appended, so falling back would double-log
            raise GraphError("staged window refused dispatch")
        self.megatick_windows += 1
        passes_base, iters, rows, conv, extra_dirty = fx
        K = handle.k
        plan_ids = {n.id for n in handle.plan}
        self._tick += K
        result = TickResult(
            tick=self._tick,
            sink_deltas={},
            passes=LazyScalar(passes_base, iters),
            dirty_nodes=len(plan_ids | extra_dirty),
            deltas_in=LazyScalar(handle.host_rows, rows),
            deltas_out=0,
            wall_s=time.perf_counter() - t0,
            quiesced=conv,
            _check_errors=self.executor.check_errors,
        )
        if _trace.ENABLED:
            _trace.evt("tick_many", t0, result.wall_s,
                       args={"ticks": K, "fused": True, "staged": True})
        self.history.append(result)
        return result

    def retire_staged(self, handle: "_StagedTicks") -> None:
        """Settle a dispatched window off the critical path: hand the
        window program's returned zeroed stack back to the ingress queue
        (placement re-assertion included) and free its generation."""
        self.executor.retire_window(handle.sw)

    def publish_metrics(self, registry=None, *, name: Optional[str]
                        = None) -> str:
        """Register live scheduler gauges (tick horizon, forced syncs,
        pending pushes) into an obs registry. Gauges only read host
        counters — never ``summarize(history)``, whose ``block()`` would
        force device syncs from the telemetry thread. Returns the
        gauge-name prefix (``sched.<graph>``)."""
        from reflow_tpu.obs import REGISTRY
        reg = registry if registry is not None else REGISTRY
        key = f"sched.{name or self.graph.name}"
        reg.gauge(f"{key}.tick", lambda: self._tick)
        reg.gauge(f"{key}.forced_syncs", lambda: self.forced_syncs)
        reg.gauge(f"{key}.pending_batches",
                  lambda: sum(len(v) for v in self._pending.values()))
        reg.gauge(f"{key}.history_len", lambda: len(self.history))
        reg.gauge(f"{key}.megatick_windows", lambda: self.megatick_windows)
        reg.gauge(f"{key}.megatick_fallbacks",
                  lambda: self.megatick_fallbacks)
        reg.gauge(f"{key}.megatick_cache_hits",
                  lambda: getattr(self.executor, "megatick_cache_hits", 0))
        self._metric_keys.append((reg, key))
        return key

    def rederive(self, source: Node, batch: DeltaBatch):
        """Invalidate-and-re-derive (the ``refresh_minmax`` pattern
        generalized to arbitrary derived state): retract ``batch``'s rows
        at ``source`` and tick, then re-insert them and tick.

        Because the retraction removes exactly the inputs that derived
        the stale state, the affected keys' derived values vanish through
        the normal exact algebra — retraction waves shrink monotonically
        (no counting-to-infinity), so the retract tick quiesces even when
        a normal incremental tick would not (e.g. an orphaned sustaining
        cycle after an SSSP edge deletion — ``workloads/sssp.repair``).
        The re-insertion then re-derives the keys from *current* upstream
        values. A tick halted at ``max_loop_iters`` beforehand is fine:
        its paused loop deltas resume inside the retract tick.

        Returns the two synchronous TickResults (retract, re-insert).
        """
        if not len(batch):
            raise GraphError("rederive needs a non-empty batch")
        self.push(source, DeltaBatch(batch.keys, batch.values,
                                     -np.asarray(batch.weights)))
        r1 = self.tick()
        self.push(source, batch)
        r2 = self.tick()
        return r1, r2

    def drain(self, source: Node, *, max_ticks: int = 256,
              probe_rows: int = 1) -> int:
        """Tick with empty (zero-weight probe) input at ``source`` until
        the graph quiesces. Flushes the residue a deferred fixpoint
        (``close_loop(defer_passes=...)``) carries across ticks: each
        drain tick runs up to ``defer_passes`` more loop passes over the
        in-flight observables, so the state converges to the same
        fixpoint a quiescent tick would have reached (docs/guide.md
        "Deferred fixpoint"). Synchronous by necessity (each round reads
        the quiescence flag back); call at stream boundaries, not inside
        a pipelined window. Returns the number of ticks used; raises if
        quiescence is not reached within ``max_ticks``."""
        if source.kind not in ("source", "loop"):
            raise GraphError(f"drain probes a source/loop, not {source}")
        # the probe must structurally reach every deferred loop's region,
        # or its ticks would report quiescence without ever running the
        # region's program (belt-and-braces: the fused program runs the
        # loop on ANY tick, but a fallback executor honors only the plan)
        deferred = [l for l in self.graph.loops if l.defer_passes]
        if deferred:
            plan_ids = {n.id for n in self._dirty_plan([source.id])}
            for l in deferred:
                if l.back_input.id not in plan_ids:
                    raise GraphError(
                        f"drain({source.name}) does not reach deferred "
                        f"loop {l.name}'s region; probe a source feeding "
                        f"that region instead")
        # probe_rows: all-zero-weight rows are semantic no-ops, so the
        # count only picks the padded capacity BUCKET — pass the steady
        # batch size to reuse an already-compiled program signature
        # instead of compiling a fresh tiny-capacity one (~60s on the
        # tunnel) just for the drain
        vshape = tuple(source.spec.value_shape)
        probe = DeltaBatch(
            np.zeros(probe_rows, np.int64),
            np.zeros((probe_rows,) + vshape, source.spec.value_dtype),
            np.zeros(probe_rows, np.int64))
        for i in range(max_ticks):
            self.push(source, probe)
            r = self.tick(sync=False).block()
            if r.quiesced:
                return i + 1
        raise GraphError(
            f"drain: {self.graph.name} not quiescent after {max_ticks} "
            f"ticks (deferred residue not converging, or the loop region "
            f"is genuinely divergent)")

    def close(self) -> None:
        """Release durable resources: just the published obs gauges
        here (the in-memory scheduler holds nothing else) — part of the
        scheduler surface so lifecycle code (``IngestFrontend.close``,
        ``ServeTier``) can shut any scheduler down uniformly;
        ``DurableScheduler`` overrides it to also seal its WAL."""
        for reg, key in self._metric_keys:
            reg.unregister_prefix(f"{key}.")
        self._metric_keys = []

    # -- host boundary out -------------------------------------------------

    def _note_forced_sync(self, context: str) -> None:
        from reflow_tpu.utils.runtime import note_forced_sync

        self.forced_syncs += 1
        note_forced_sync(context)

    def read_table(self, node: Node) -> Dict:
        """Materialized {key: value} of a stateful node's collection at the
        tick boundary (Reduce: last emitted aggregates; Join: the left
        table). This is the sink-style host crossing for collections that
        live inside loop regions, where a per-pass delta sink would force
        mid-tick readbacks."""
        if getattr(self.executor, "name", "") != "cpu":
            self._note_forced_sync("read_table")
        return self.executor.read_table(node)

    def view(self, sink: str | Node) -> Counter:
        """Materialized multiset {(key, value): weight} at a sink."""
        name = sink if isinstance(sink, str) else sink.name
        return self.sink_views[name]

    def refresh_minmax(self, node: Node, batch: DeltaBatch) -> None:
        """Maintenance: rebuild a buffered min/max Reduce's candidate
        buffers for every key in ``batch`` from a replay of its full live
        multiset, resetting the monotone overflow latches (device
        executors; the exact CPU oracle ignores it). Keeps long-running
        heavy-churn keys exact instead of eventually tripping the loud
        buffer-exhaustion error. Call between ticks."""
        from reflow_tpu.executors.lowerings import LINEAR_DEVICE_REDUCERS
        from reflow_tpu.graph import GraphError

        if (node.kind != "op" or node.op.kind != "reduce"
                or node.op.how in LINEAR_DEVICE_REDUCERS):
            raise GraphError(f"{node}: refresh_minmax needs a min/max "
                             f"Reduce node")
        self.executor.refresh_minmax(node, batch)

    def view_dict(self, sink: str | Node) -> Dict:
        """Materialized {key: value} for unique-keyed sink collections."""
        d: Dict = {}
        for (k, v), w in self.view(sink).items():
            if w > 0:
                if k in d:
                    raise GraphError(f"sink {sink} is not unique-keyed at {k!r}")
                d[k] = v
        return d
