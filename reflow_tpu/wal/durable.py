"""DurableScheduler: a DirtyScheduler whose ingestion survives crashes.

Ordering is the whole design: the WAL append happens *before* the base
scheduler accepts a push, so every accepted batch is durable by the time
``push`` returns True. The failure window decomposes as:

- crash **before** the append: the batch was never accepted — upstream
  never got an ack and re-sends after recovery; folded once.
- crash **during** the append (torn record): same as above — the torn
  frame is dropped at scan time, the re-send is accepted once.
- crash **between** append and accept, or between ``push`` and
  ``tick``: recovery replays the record into pending; the upstream
  re-send then dedups against the replayed ``batch_id``. Folded once.
- crash **mid-tick** (no ``tick`` marker yet): recovery replays the
  pushes and re-runs the tick deterministically from the checkpoint
  state.

Exactly-once across process death therefore needs nothing from the
caller beyond what lossy-transport exactly-once already needed: stable
``batch_id``s (mint them with ``scheduler.SourceCursor``). Pushes
without an id get an auto-minted ``__wal__<source>@<n>`` id so replay
still dedups — but the *caller's* re-send of such a batch cannot be
recognized, so end-to-end exactly-once requires caller-supplied ids.

Crash-point injection (``crash=utils.faults.CrashInjector(...)``) fires
at the named seams above; ``utils.faults.tear_wal_tail`` tears the final
record after the fact. Together they drive the crash-recovery
differential tests.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.graph import Node
from reflow_tpu.scheduler import DirtyScheduler, TickResult
from reflow_tpu.wal.log import WriteAheadLog

__all__ = ["DurableScheduler"]


class DurableScheduler(DirtyScheduler):
    """DirtyScheduler + write-ahead logging of accepted source batches.

    ``fsync`` picks the durability/latency point (log.py's contract):
    ``"record"`` / ``"tick"`` (default) / ``"os"``. Device-resident
    batches are materialized to host before logging — durability needs
    the bytes, and that readback is a forced sync on a tunnel runtime;
    keep WAL ingestion on host-side batches for streaming workloads.
    """

    def __init__(self, graph, executor=None, *, wal_dir: str,
                 fsync: str = "tick", segment_bytes: int = 16 << 20,
                 crash=None, **kwargs):
        super().__init__(graph, executor, **kwargs)
        self.wal = WriteAheadLog(wal_dir, fsync=fsync,
                                 segment_bytes=segment_bytes)
        self._crash = crash
        self._wal_suspended = False  # recovery replay must not re-log
        self._auto_seq = 0

    # -- crash-point seam --------------------------------------------------

    def _crash_point(self, name: str) -> None:
        if self._crash is not None:
            self._crash.point(name)

    # -- ingestion ---------------------------------------------------------

    def _mint_auto_id(self, source: Node) -> str:
        # skip past ids a recovered dedup window already holds, so a
        # restarted driver never mints an id that would dedup away
        while True:
            bid = f"__wal__{source.name}@{self._auto_seq}"
            self._auto_seq += 1
            if bid not in self._seen_batch_ids:
                return bid

    def _log_push(self, source: Node, batch: DeltaBatch,
                  batch_id: str) -> DeltaBatch:
        if hasattr(batch, "nonzero"):  # device-resident: forced readback
            batch = self.executor.materialize(batch)
        self._crash_point("before_append")
        self.wal.append({
            "kind": "push",
            "tick": self._tick,
            "node": source.id,
            "node_name": source.name,
            "batch_id": batch_id,
            "keys": batch.keys,
            "values": batch.values,
            "weights": batch.weights,
        })
        self._crash_point("after_append")
        return batch

    def push(self, source: Node, batch: DeltaBatch, *,
             batch_id: Optional[str] = None) -> bool:
        if self._wal_suspended:
            return super().push(source, batch, batch_id=batch_id)
        if source.kind not in ("source", "loop"):
            # fail before logging what the base scheduler would reject
            return super().push(source, batch, batch_id=batch_id)
        if batch_id is None:
            batch_id = self._mint_auto_id(source)
        elif batch_id in self._seen_batch_ids:
            return False  # duplicate: nothing to make durable
        batch = self._log_push(source, batch, batch_id)
        accepted = super().push(source, batch, batch_id=batch_id)
        self._crash_point("after_push")
        return accepted

    # -- tick boundary -----------------------------------------------------

    def _log_tick_mark(self) -> None:
        self._crash_point("before_tick_mark")
        self.wal.append({"kind": "tick", "tick": self._tick})
        self.wal.note_tick()  # the per-tick durability barrier
        self._crash_point("after_tick")

    def tick(self, **kwargs) -> TickResult:
        result = super().tick(**kwargs)
        if not self._wal_suspended:
            self._log_tick_mark()
        return result

    def tick_many(self, feeds: Sequence[Dict[Node, DeltaBatch]], *,
                  feed_ids=None) -> TickResult:
        if self._wal_suspended:
            return super().tick_many(feeds, feed_ids=feed_ids)
        # feeds bypass push(), so log them here first (append-before-
        # accept, same as push). ``feed_ids`` carries the producer batch
        # ids a coalesced feed entry commits (serve frontend); entries
        # without ids get an auto id so the replay is still idempotent.
        # The whole window is one wal.append_group — under
        # fsync="record" that is ONE fsync for the window (group
        # commit), not one per micro-batch. Device-resident feeds get
        # materialized — a forced sync that negates the macro-tick's
        # pipelining; durable ingestion wants host-side feeds.
        ids_seq = feed_ids if feed_ids is not None else [{}] * len(feeds)
        logged, records = [], []
        for feed, ids_map in zip(feeds, ids_seq):
            entry = {}
            for src, b in feed.items():
                ids = list(ids_map.get(src, ())) or [self._mint_auto_id(src)]
                if hasattr(b, "nonzero"):  # device-resident: forced readback
                    b = self.executor.materialize(b)
                entry[src] = b
                rec = {
                    "kind": "push",
                    "tick": self._tick,
                    "node": src.id,
                    "node_name": src.name,
                    "batch_id": ids[0],
                    "keys": b.keys,
                    "values": b.values,
                    "weights": b.weights,
                }
                if len(ids) > 1:
                    # several micro-batches coalesced into this one feed
                    # batch: their ids commit (and replay) atomically
                    rec["batch_ids"] = ids
                records.append(rec)
            logged.append(entry)
        self._crash_point("before_append")
        self.wal.append_group(records)
        self._crash_point("after_append")
        # suspend the per-tick overrides during execution: the fallback
        # path runs self.tick() per feed, and its per-tick markers would
        # duplicate the window markers appended below
        self._wal_suspended = True
        try:
            result = super().tick_many(logged, feed_ids=feed_ids)
        finally:
            self._wal_suspended = False
        tick_now = self._tick
        self.wal.append_group([
            {"kind": "tick", "tick": t}
            for t in range(tick_now - len(feeds) + 1, tick_now + 1)])
        self.wal.note_tick()
        self._crash_point("after_tick")
        return result

    def close(self) -> None:
        """Durably flush and seal the log (clean shutdown). Idempotent —
        the serving frontend's ``close()`` and a caller's own shutdown
        path may both reach it."""
        self.wal.close()
