"""DurableScheduler: a DirtyScheduler whose ingestion survives crashes.

Ordering is the whole design: the WAL append happens *before* the base
scheduler accepts a push, so every accepted batch is durable by the time
``push`` returns True. The failure window decomposes as:

- crash **before** the append: the batch was never accepted — upstream
  never got an ack and re-sends after recovery; folded once.
- crash **during** the append (torn record): same as above — the torn
  frame is dropped at scan time, the re-send is accepted once.
- crash **between** append and accept, or between ``push`` and
  ``tick``: recovery replays the record into pending; the upstream
  re-send then dedups against the replayed ``batch_id``. Folded once.
- crash **mid-tick** (no ``tick`` marker yet): recovery replays the
  pushes and re-runs the tick deterministically from the checkpoint
  state.
- crash **between write and fsync** (the asynchronous committer): the
  execute may have finished, but acknowledgement gates on
  ``wal.wait_durable`` — so the caller's ticket is still unresolved,
  the upstream re-sends, and replay (of whatever prefix survived)
  dedups. Folded once.

Exactly-once across process death therefore needs nothing from the
caller beyond what lossy-transport exactly-once already needed: stable
``batch_id``s (mint them with ``scheduler.SourceCursor``). Pushes
without an id get an auto-minted ``__wal__<source>@<n>`` id so replay
still dedups — but the *caller's* re-send of such a batch cannot be
recognized, so end-to-end exactly-once requires caller-supplied ids.

Device-resident batches and pre-images (ROADMAP: "log device-resident
batches without a forced sync"): durability needs the host bytes, but a
readback of a device batch is a forced sync — on a tunnel runtime the
degrading first-sync. The fix is **ingest-time pre-image logging**:
whoever uploaded the batch had the host payload first; hand it to
:meth:`DurableScheduler.push_preimage` (the serve frontend does this
automatically from ``submit(..., preimage=...)``) and the WAL logs that
pre-image while the device batch flows on untouched.
``log_readbacks`` counts the fallback materializations — zero on a
well-formed streaming path (the ``REFLOW_BENCH_WALPIPE=1`` assertion).

Crash-point injection (``crash=utils.faults.CrashInjector(...)``) fires
at the named seams above plus the WAL's own pipeline seams:
``wal_enqueue`` on the appending thread (the frame is queued, nothing
is on disk yet), then ``wal_before_write`` / ``wal_after_write`` and
``wal_before_fsync`` / ``wal_after_fsync`` on the committer thread
(inline committers fire the write/fsync seams on the appender itself);
``utils.faults.tear_wal_tail`` tears the final record after the fact.
Together they drive the crash-recovery differential tests.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.graph import Node
from reflow_tpu.scheduler import DirtyScheduler, TickResult
from reflow_tpu.wal.log import WriteAheadLog

__all__ = ["DurableScheduler"]


class DurableScheduler(DirtyScheduler):
    """DirtyScheduler + write-ahead logging of accepted source batches.

    ``fsync`` picks the durability/latency point (log.py's contract):
    ``"record"`` / ``"tick"`` (default) / ``"os"``. ``committer`` picks
    where the fsync runs: ``"thread"`` (default — pipelined, off the
    dispatch path) or ``"inline"`` (synchronous, the pre-pipeline
    behavior). Device-resident batches log their host **pre-image**
    when one was registered (:meth:`push_preimage`); without one they
    are materialized to host — a forced readback the streaming path
    must avoid (``log_readbacks`` counts them).
    """

    def __init__(self, graph, executor=None, *, wal_dir: str,
                 fsync: str = "tick", segment_bytes: int = 16 << 20,
                 committer: str = "thread", crash=None, epoch: int = 0,
                 **kwargs):
        super().__init__(graph, executor, **kwargs)
        self.wal = WriteAheadLog(wal_dir, fsync=fsync,
                                 segment_bytes=segment_bytes,
                                 committer=committer, crash=crash,
                                 epoch=epoch)
        self._crash = crash
        self._wal_suspended = False  # recovery replay must not re-log
        self._auto_seq = 0
        #: batch_id -> host pre-image of an uploaded device batch,
        #: consumed (popped) when that batch is logged
        self._preimages: Dict[str, DeltaBatch] = {}
        #: batch_id -> causality token (obs.trace.mint_cause) to stamp
        #: onto that batch's WAL push record, consumed when logged —
        #: replicas and the shipper re-emit the token so the trace
        #: chain stitches across processes (tracing-on only; replay
        #: ignores unknown record keys)
        self._causes: Dict[str, str] = {}
        #: forced host readbacks on the logging path (device batch, no
        #: pre-image) — the streaming zero-readback property's counter
        self.log_readbacks = 0

    # -- crash-point seam --------------------------------------------------

    def _crash_point(self, name: str) -> None:
        if self._crash is not None:
            self._crash.point(name)

    @property
    def epoch(self) -> int:
        """Leader epoch stamped into every appended record — the WAL
        owns it (promotion mints the new one there). Surfaced so the
        ingestion RPC's hello can advertise the true epoch: producer
        causality tokens minted after a failover must carry the new
        epoch, not 0."""
        return self.wal.epoch

    # -- ingestion ---------------------------------------------------------

    def _mint_auto_id(self, source: Node) -> str:
        # skip past ids a recovered dedup window already holds, so a
        # restarted driver never mints an id that would dedup away
        while True:
            bid = f"__wal__{source.name}@{self._auto_seq}"
            self._auto_seq += 1
            if bid not in self._seen_batch_ids:
                return bid

    def push_preimage(self, batch_id: str, batch: DeltaBatch) -> None:
        """Register the host-side pre-image of a device batch about to
        be pushed (or submitted) under ``batch_id``: the WAL logs these
        bytes instead of reading the device copy back. The caller owns
        the equivalence — the pre-image must be the exact batch that was
        uploaded. Consumed by the next log of that id; unused pre-images
        are dropped when their id resolves (dedup) or the log is
        sealed."""
        if hasattr(batch, "nonzero"):
            raise ValueError(
                f"pre-image for {batch_id!r} is itself device-resident; "
                f"pass the host DeltaBatch that was uploaded")
        self._preimages[batch_id] = batch

    def push_cause(self, batch_id: str, cause: str) -> None:
        """Register the causality token riding ``batch_id`` (the serve
        frontend does this for sampled tickets): the batch's WAL push
        record is stamped with it, so the shipper and every replica
        replaying the record can re-emit the same token. Consumed by
        the next log of that id; dropped on dedup or seal."""
        self._causes[batch_id] = cause

    def _record_causes(self, ids) -> list:
        """Pop the registered tokens of a record's batch ids (one per
        sampled micro-batch; coalesced records may carry several)."""
        out = []
        for bid in ids:
            c = self._causes.pop(bid, None)
            if c is not None:
                out.append(c)
        return out

    def _host_image(self, batch, batch_id: str):
        """(host_bytes_for_log, batch_to_execute): a device batch with a
        registered pre-image logs the pre-image and executes untouched;
        without one it is materialized (counted) and the host copy both
        logs and executes — the legacy forced-readback path."""
        if not hasattr(batch, "nonzero"):
            self._preimages.pop(batch_id, None)
            return batch, batch
        pre = self._preimages.pop(batch_id, None)
        if pre is not None:
            return pre, batch
        self.log_readbacks += 1
        host = self.executor.materialize(batch)
        return host, host

    def _log_push(self, source: Node, batch: DeltaBatch,
                  batch_id: str) -> DeltaBatch:
        image, batch = self._host_image(batch, batch_id)
        self._crash_point("before_append")
        rec = {
            "kind": "push",
            "tick": self._tick,
            "node": source.id,
            "node_name": source.name,
            "batch_id": batch_id,
            "keys": image.keys,
            "values": image.values,
            "weights": image.weights,
        }
        causes = self._record_causes((batch_id,))
        if causes:
            rec["cause"] = causes[0]
        self.wal.append(rec)
        self._crash_point("after_append")
        return batch

    def push(self, source: Node, batch: DeltaBatch, *,
             batch_id: Optional[str] = None) -> bool:
        if self._wal_suspended:
            return super().push(source, batch, batch_id=batch_id)
        if source.kind not in ("source", "loop"):
            # fail before logging what the base scheduler would reject
            return super().push(source, batch, batch_id=batch_id)
        if batch_id is None:
            batch_id = self._mint_auto_id(source)
        elif batch_id in self._seen_batch_ids:
            self._preimages.pop(batch_id, None)
            self._causes.pop(batch_id, None)
            return False  # duplicate: nothing to make durable
        batch = self._log_push(source, batch, batch_id)
        accepted = super().push(source, batch, batch_id=batch_id)
        self._crash_point("after_push")
        return accepted

    # -- tick boundary -----------------------------------------------------

    def _log_tick_mark(self) -> None:
        self._crash_point("before_tick_mark")
        self.wal.append({"kind": "tick", "tick": self._tick})
        self.wal.note_tick()  # the per-tick durability barrier
        self._crash_point("after_tick")

    def tick(self, **kwargs) -> TickResult:
        result = super().tick(**kwargs)
        if not self._wal_suspended:
            self._log_tick_mark()
        return result

    def tick_many(self, feeds: Sequence[Dict[Node, DeltaBatch]], *,
                  feed_ids=None, wait_durable: bool = True) -> TickResult:
        """``wait_durable=False`` is the pipelined-commit entry (the
        serve frontend): the window's records and tick markers are
        written + flushed and their durability REQUEST is enqueued, but
        this call returns without blocking on the fsync. The caller must
        gate every acknowledgement on ``wal.wait_durable(lsn)`` /
        ``wal.when_durable(lsn, ...)`` with ``lsn = wal.last_lsn()``
        read right after this returns — so window N's fsync overlaps
        window N+1's host merge and dispatch."""
        if self._wal_suspended:
            return super().tick_many(feeds, feed_ids=feed_ids)
        # feeds bypass push(), so log them here first (append-before-
        # accept, same as push). ``feed_ids`` carries the producer batch
        # ids a coalesced feed entry commits (serve frontend); entries
        # without ids get an auto id so the replay is still idempotent.
        # The whole window is one wal.append_group — under
        # fsync="record" that is ONE fsync for the window (group
        # commit), not one per micro-batch. Device-resident feeds log
        # their registered pre-image (no readback); only an unregistered
        # device feed pays the forced materialize.
        logged, records = self._window_records(feeds, feed_ids)
        self._crash_point("before_append")
        # request=False: the window is ONE logical commit — the marker
        # group below carries the single durability barrier covering
        # data + markers (acknowledgement gates on the marker LSN)
        self.wal.append_group(records, wait=False, request=False)
        self._crash_point("after_append")
        # suspend the per-tick overrides during execution: the fallback
        # path runs self.tick() per feed, and its per-tick markers would
        # duplicate the window markers appended below
        self._wal_suspended = True
        try:
            result = super().tick_many(logged, feed_ids=feed_ids)
        finally:
            self._wal_suspended = False
        tick_now = self._tick
        self.wal.append_group([
            {"kind": "tick", "tick": t}
            for t in range(tick_now - len(feeds) + 1, tick_now + 1)],
            wait=False)
        self.wal.note_tick(wait=False)
        if wait_durable:
            self.wal.wait_durable(self.wal.last_lsn())
        self._crash_point("after_tick")
        return result

    def _window_records(self, feeds, feed_ids):
        """Build one window's WAL push records (and the executable feed
        maps with device batches swapped for their logged host images).
        Shared between ``tick_many`` and the staged pipeline's
        ``_log_window_feeds``."""
        ids_seq = feed_ids if feed_ids is not None else [{}] * len(feeds)
        logged, records = [], []
        for feed, ids_map in zip(feeds, ids_seq):
            entry = {}
            for src, b in feed.items():
                ids = list(ids_map.get(src, ())) or [self._mint_auto_id(src)]
                image, b = self._host_image(b, ids[0])
                entry[src] = b
                rec = {
                    "kind": "push",
                    "tick": self._tick,
                    "node": src.id,
                    "node_name": src.name,
                    "batch_id": ids[0],
                    "keys": image.keys,
                    "values": image.values,
                    "weights": image.weights,
                }
                if len(ids) > 1:
                    # several micro-batches coalesced into this one feed
                    # batch: their ids commit (and replay) atomically
                    rec["batch_ids"] = ids
                causes = self._record_causes(ids)
                if causes:
                    rec["cause"] = causes[0]
                    if len(causes) > 1:
                        rec["causes"] = tuple(causes)
                records.append(rec)
            logged.append(entry)
        return logged, records

    # -- staged (pipelined) windows ----------------------------------------

    def _log_window_feeds(self, feeds, feed_ids) -> None:
        """Append a staged window's push records before its dispatch —
        the same append-before-dispatch order, grouping, and single
        durability barrier as ``tick_many`` (request=False here; the
        marker group appended by ``dispatch_staged`` carries the
        window's one durability request). ``stage_window`` rejects
        device-resident feeds before reaching this, so no materialize
        readbacks can occur here."""
        if self._wal_suspended:
            return
        _, records = self._window_records(feeds, feed_ids)
        self._crash_point("before_append")
        self.wal.append_group(records, wait=False, request=False)
        self._crash_point("after_append")

    def dispatch_staged(self, handle):
        """Dispatch a staged window and append its K tick markers. Never
        blocks on the fsync (the pipelined-commit contract): the caller
        gates acknowledgements on ``wal.when_durable(wal.last_lsn(), …)``
        read right after this returns."""
        result = super().dispatch_staged(handle)
        if not self._wal_suspended:
            tick_now = self._tick
            self.wal.append_group([
                {"kind": "tick", "tick": t}
                for t in range(tick_now - handle.k + 1, tick_now + 1)],
                wait=False)
            self.wal.note_tick(wait=False)
            self._crash_point("after_tick")
        return result

    def close(self) -> None:
        """Durably flush and seal the log (clean shutdown). Idempotent —
        the serving frontend's ``close()`` and a caller's own shutdown
        path may both reach it."""
        self._preimages.clear()
        self._causes.clear()
        self.wal.close()
