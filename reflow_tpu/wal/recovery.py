"""Crash recovery: checkpoint restore + WAL tail replay.

``recover(sched, wal_dir, ckpt_dir)`` rebuilds a crashed process's
scheduler in two moves:

1. **Restore** the latest checkpoint (if one exists) — operator state,
   sink views, tick counter, dedup window, pending batches — and take
   its recorded WAL position as the replay start.
2. **Replay** the WAL tail through the scheduler's ordinary
   ``push(batch_id=...)`` / ``tick()`` path. Idempotence needs no new
   machinery: a push whose id the restored dedup window already holds
   is dropped by the same code that drops a lossy transport's
   duplicates, and a tick marker at or below the restored tick counter
   is skipped. Execution is deterministic from the restored state, so
   the re-run ticks reproduce exactly the sink deltas the crashed
   process produced.

Pushes logged after the last tick marker (a crash between ``push`` and
``tick``) land back in the pending buffers, exactly where the crash
left them; the next ``tick()`` folds them once.

The asynchronous WAL committer changes nothing here: a crash between a
frame's write and its fsync may leave the scan seeing records whose
submitters were never acknowledged (their tickets were still gated on
``wal.wait_durable``). Replaying them is safe — replay is idempotent,
and the upstream's re-send of the unacknowledged batch dedups against
the replayed ``batch_id``. Conversely a power loss may drop
written-but-unfsynced frames entirely; those batches were never
acknowledged either, so the re-send folds them exactly once.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from reflow_tpu.delta import DeltaBatch
from reflow_tpu.wal.log import TornTail, WalError, scan_wal

__all__ = ["RecoveryReport", "recover", "replay_records"]


@dataclasses.dataclass
class RecoveryReport:
    """What a ``recover()`` call did (metrics.summarize_wal merges
    these counters into the WAL metrics record)."""

    checkpoint_loaded: bool
    checkpoint_tick: int
    wal_records: int
    replayed_pushes: int
    deduped_pushes: int
    replayed_ticks: int
    skipped_ticks: int
    torn_tail: Optional[TornTail]
    final_tick: int
    #: highest epoch stamped on any scanned record (0 = pre-fencing
    #: log); the recovering WAL adopts it so a restarted leader can
    #: never write records older than what its own log already holds
    epoch: int = 0

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["torn_tail"] = (self.torn_tail._asdict()
                          if self.torn_tail is not None else None)
        return d


def _resolve_source(sched, rec):
    node = sched.graph.nodes[rec["node"]]
    if node.name != rec["node_name"]:
        raise ValueError(
            f"WAL push record for node #{rec['node']} named "
            f"{rec['node_name']!r}, but the recovering graph has "
            f"{node.name!r} there — recover() needs the same graph the "
            f"log was written against")
    return node


def replay_records(sched, records) -> tuple:
    """Replay scanned WAL records through ``sched``'s ordinary
    ``push(batch_id=...)`` / ``tick()`` path — the idempotent core shared
    by :func:`recover` and the read replicas' continuous replay
    (``serve/replica.py``). ``records`` is an iterable of ``(pos, rec)``
    pairs (positions are ignored; a bare record iterable also works when
    each element is a 2-tuple ending in the record dict). A
    ``DurableScheduler`` caller must suspend its own re-logging around
    this (``recover`` does; replicas run a plain scheduler). Returns
    ``(replayed_pushes, deduped_pushes, replayed_ticks, skipped_ticks)``.
    """
    replayed = deduped = ticks_done = ticks_skipped = 0
    for _pos, rec in records:
        kind = rec.get("kind")
        if kind == "push":
            batch = DeltaBatch(rec["keys"], rec["values"],
                               rec["weights"])
            node = _resolve_source(sched, rec)
            ids = rec.get("batch_ids")
            if ids is None:
                if sched.push(node, batch, batch_id=rec["batch_id"]):
                    replayed += 1
                else:
                    deduped += 1
            elif any(b in sched._seen_batch_ids for b in ids):
                # a coalesced frontend feed batch: its micro-batch
                # ids committed atomically with the macro-tick, so
                # the replay is all-or-nothing too
                if (rec.get("compacted")
                        and not all(b in sched._seen_batch_ids
                                    for b in ids)):
                    # a key-level-folded record (wal/compact.py) whose
                    # ids this scheduler has PARTIALLY seen cannot be
                    # replayed: the folded batch is the sum of all its
                    # inputs and has no per-id slice to apply. The
                    # supported flows keep fold ids disjoint from any
                    # restore point (folds start at the checkpoint
                    # anchor; re-anchored followers reset through the
                    # checkpoint) — hitting this means replaying a
                    # compacted log against a state cut inside the
                    # folded range. Fail loud over silent divergence.
                    raise WalError(
                        f"compacted record for {rec['node_name']!r} has "
                        f"{sum(1 for b in ids if b in sched._seen_batch_ids)}"
                        f"/{len(ids)} already-seen batch ids — state "
                        f"cut lands inside a folded range; restore "
                        f"from the checkpoint anchor instead")
                deduped += 1
            else:
                for b in ids:
                    sched._register_batch_id(b)
                sched.push(node, batch)
                replayed += 1
        elif kind == "tick":
            if rec["tick"] > sched._tick:
                sched.tick()
                ticks_done += 1
            else:
                ticks_skipped += 1
        # "ckpt" and unknown kinds: informational, skip
    return replayed, deduped, ticks_done, ticks_skipped


def recover(sched, wal_dir: str, ckpt_dir: Optional[str] = None,
            ) -> RecoveryReport:
    """Restore ``sched`` (fresh, same graph/executor as the crashed run)
    from the latest checkpoint plus the WAL tail. Works on a plain
    ``DirtyScheduler`` or a ``DurableScheduler`` (whose re-logging is
    suspended during replay — the tail segments stay authoritative
    until the next checkpoint truncates them)."""
    from reflow_tpu.utils.checkpoint import (checkpoint_exists,
                                             load_checkpoint)

    start = None
    ckpt_loaded = False
    ckpt_tick = 0
    if ckpt_dir is not None and checkpoint_exists(ckpt_dir):
        # dispatches on layout: a legacy full checkpoint or an
        # incremental chain (base + deltas); either way ``wal_pos`` is
        # the scan anchor and the tail past it may be compacted —
        # replay of folded records goes through the same dedup below
        meta = load_checkpoint(sched, ckpt_dir)
        ckpt_loaded = True
        ckpt_tick = sched._tick
        start = meta.get("wal_pos")

    records, torn = scan_wal(wal_dir, start=start)
    if torn is None:
        # a DurableScheduler already repaired the crashed generation's
        # torn tail when it opened the log; surface that here
        torn = getattr(getattr(sched, "wal", None), "repaired_tail", None)
    suspended = getattr(sched, "_wal_suspended", None)
    if suspended is not None:
        sched._wal_suspended = True
    try:
        replayed, deduped, ticks_done, ticks_skipped = replay_records(
            sched, records)
    finally:
        if suspended is not None:
            sched._wal_suspended = False
    max_epoch = max((rec.get("epoch", 0) or 0 for _p, rec in records),
                    default=0)
    wal = getattr(sched, "wal", None)
    if wal is not None and hasattr(wal, "adopt_epoch"):
        wal.adopt_epoch(max_epoch)
        max_epoch = wal.epoch
    return RecoveryReport(
        checkpoint_loaded=ckpt_loaded,
        checkpoint_tick=ckpt_tick,
        wal_records=len(records),
        replayed_pushes=replayed,
        deduped_pushes=deduped,
        replayed_ticks=ticks_done,
        skipped_ticks=ticks_skipped,
        torn_tail=torn,
        final_tick=sched._tick,
        epoch=max_epoch,
    )
