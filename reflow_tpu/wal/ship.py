"""WAL shipping: stream the durable log prefix to read replicas.

The pipelined committer (``wal/log.py``) already maintains a *synced*
watermark — the LSN below which every frame is written AND fsynced.
:meth:`WriteAheadLog.synced_position` exposes its byte-position twin,
and everything strictly before that ``(segment, offset)`` is exactly the
prefix a follower may safely mirror: bytes past it may still be sitting
in the committer queue or the page cache, and a power loss could take
them back (shipping them would let a replica serve state the leader
itself forgets on restart).

:class:`SegmentShipper` tails that watermark and streams the prefix to
N followers over a deliberately dumb, resumable protocol:

- ``follower.subscribe()`` returns the follower's persisted cursor
  (leader WAL coordinates) or ``None`` for a fresh replica. Fresh
  replicas are **checkpoint-anchored**: if the leader keeps checkpoints,
  the shipper calls ``follower.bootstrap(ckpt_dir)`` so catch-up replays
  only the WAL tail, not history from segment 0. Cursor coordinates are
  shared between leader and mirror by construction — a checkpoint's
  recorded ``wal_pos`` is always a segment *start* (``save_checkpoint``
  rotates first), so both sides agree on every byte after it.
- Each :class:`Shipment` is a run of raw CRC-framed bytes from one
  segment (no magic header), re-verified by the shipper before it leaves
  and by the receiver before it lands. ``seals=True`` marks the end of a
  sealed segment; ``next_segment`` tells the follower where the log
  continues (segment numbering may skip across leader restarts).
- The receiver answers :class:`ShipAck` (cursor advanced, new replay
  horizon) or :class:`ShipNack` (out-of-order or CRC-rejected). A NACK
  carries the receiver's authoritative cursor; the shipper re-reads from
  there off disk and resends — the WAL itself is the retransmit buffer,
  so the shipper keeps no in-flight state worth losing.

Transport is in-process (followers are objects, shipping is a thread —
same stance as the serve tier's pump pool); the protocol above is the
part that matters, and it is exercised torn/tampered/killed in
``tests/test_replica.py``.

The shipper persists ``ship-state.json`` next to the leader's segments
so ``tools/wal_inspect.py`` can report shipped/applied watermarks
without importing any of this.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

from reflow_tpu.obs import trace as _trace
from reflow_tpu.utils.config import env_int
from reflow_tpu.utils.runtime import named_lock
from reflow_tpu.obs.registry import REGISTRY
from reflow_tpu.wal.compact import (COMPACT_MANIFEST_FILE,
                                    read_compact_manifest)
from reflow_tpu.wal.log import (_HEADER, _MAGIC, LogPosition, WalError,
                                list_segments)

__all__ = ["Shipment", "ShipAck", "ShipNack", "SegmentShipper",
           "iter_frames", "record_causes", "SHIP_STATE_FILE",
           "SHIP_STATE_SCHEMA"]

SHIP_STATE_FILE = "ship-state.json"
SHIP_STATE_SCHEMA = "reflow.wal_ship/1"

_MAX_FRAME = 64 << 20  # sanity bound mirroring log._MAX_RECORD


class Shipment(NamedTuple):
    """One run of raw CRC-framed bytes from a single leader segment.

    ``payload`` covers leader bytes ``[offset, end_offset)`` of
    ``segment`` and always ends on a frame boundary. ``seals`` marks
    that this shipment reaches the end of a sealed segment, in which
    case ``next_segment`` is where the log continues. ``leader_tick``
    piggybacks the leader's tick counter so receivers can publish a lag
    gauge without a second channel. ``epoch`` is the shipping leader's
    epoch token (``wal/log.py`` fencing): a receiver rejects shipments
    from an epoch below its own — a fenced zombie's bytes are never
    merged. ``cause`` is an opaque causality token
    (``obs.trace.mint_cause``) stamped only while tracing is enabled so
    the ship → send → replay spans of one chunk stitch into a single
    cross-process chain; receivers echo it into their replay span and
    otherwise ignore it. Both trailing fields are defaulted so
    pre-epoch / pre-trace constructors stay valid."""

    segment: int
    offset: int
    payload: bytes
    end_offset: int
    seals: bool
    next_segment: Optional[int]
    leader_tick: int
    epoch: int = 0
    cause: Optional[str] = None


class ShipAck(NamedTuple):
    """Receiver accepted a shipment: ``cursor`` is its new resume
    position (leader coordinates), ``horizon`` its published tick
    horizon after applying any completed commit windows."""

    cursor: Tuple[int, int]
    horizon: int


class ShipNack(NamedTuple):
    """Receiver rejected a shipment (cursor mismatch or CRC failure).
    ``cursor`` is the receiver's authoritative resume position — the
    shipper re-reads from there and resends."""

    cursor: Optional[Tuple[int, int]]
    reason: str


def iter_frames(payload: bytes, segment: int, base: int,
                ) -> Tuple[List[Tuple[LogPosition, LogPosition, dict]],
                           int, Optional[str]]:
    """Walk ``payload`` (raw frames, no magic) as leader bytes starting
    at ``(segment, base)``. Returns ``(entries, valid_len, reason)``
    where each entry is ``(pos, end_pos, record)``; ``valid_len <
    len(payload)`` means the walk stopped early for ``reason`` (torn
    header, short payload, CRC mismatch, unpicklable record)."""
    import pickle

    entries: List[Tuple[LogPosition, LogPosition, dict]] = []
    off = 0
    n = len(payload)
    while off < n:
        if off + _HEADER.size > n:
            return entries, off, "truncated frame header"
        length, crc = _HEADER.unpack_from(payload, off)
        if length > _MAX_FRAME:
            return entries, off, f"implausible frame length {length}"
        body = payload[off + _HEADER.size: off + _HEADER.size + length]
        if len(body) < length:
            return entries, off, (f"truncated payload "
                                  f"({len(body)}/{length} bytes)")
        if zlib.crc32(body) != crc:
            return entries, off, "CRC mismatch"
        try:
            rec = pickle.loads(body)
        except Exception as e:  # noqa: BLE001 - framed yet unloadable
            return entries, off, f"unpicklable payload ({e})"
        end = off + _HEADER.size + length
        entries.append((LogPosition(segment, base + off),
                        LogPosition(segment, base + end), rec))
        off = end
    return entries, off, None


def record_causes(rec) -> List[str]:
    """Causality tokens stamped on one WAL push record
    (``DurableScheduler.push_cause``): the singular ``cause`` plus any
    coalesced ``causes`` overflow, deduplicated in order. Empty for
    unstamped (tracing-off) records."""
    if not isinstance(rec, dict):
        return []
    out: List[str] = []
    c = rec.get("cause")
    if c:
        out.append(c)
    for x in rec.get("causes") or ():
        if x not in out:
            out.append(x)
    return out


class _FollowerState:
    __slots__ = ("name", "follower", "cursor", "applied_horizon",
                 "bytes_total", "shipments", "nacks", "bootstraps",
                 "fenced", "high_water", "retransmit_bytes",
                 "link_stalls", "anchor_gen", "compact_reanchors")

    def __init__(self, name: str, follower) -> None:
        self.name = name
        self.follower = follower
        self.cursor: Optional[LogPosition] = None
        self.applied_horizon = 0
        self.bytes_total = 0
        self.shipments = 0
        self.nacks = 0
        self.bootstraps = 0
        #: the follower rejected our epoch as stale: this shipper is a
        #: zombie ex-leader's — stop re-offering, the bytes will never
        #: be accepted (retrying would NACK-spin forever)
        self.fenced = False
        #: furthest position ever offered to this follower: a chunk
        #: starting below it is a retransmission (NACK resync or
        #: ack-lost duplicate), counted in ``retransmit_bytes``
        self.high_water: Optional[LogPosition] = None
        self.retransmit_bytes = 0
        #: receive() returned None — link-level no-progress (down,
        #: mid-backoff, reset mid-exchange); NOT a protocol NACK
        self.link_stalls = 0
        #: compaction generation this follower's cursor was anchored
        #: under (-1 for a persisted-cursor attach, where the era is
        #: unknown and any compacted segment forces a conservative
        #: re-anchor). Mid-segment offsets from an older generation
        #: point into bytes a compaction pass rewrote.
        self.anchor_gen = -1
        self.compact_reanchors = 0


class SegmentShipper:
    """Tail the leader WAL's synced watermark and stream the durable
    prefix to attached followers.

    ``wal`` is the leader's :class:`WriteAheadLog` (or ``None`` for a
    cold log: pass ``wal_dir`` and the shipper treats the whole on-disk
    prefix as shippable — useful for tools and tests). ``ckpt_dir``
    enables checkpoint-anchored bootstrap for fresh followers.
    ``leader_tick`` is a callable returning the leader's current tick
    counter (piggybacked on shipments for lag gauges).

    Drive it either with the background thread (``start()`` /
    ``stop()``) or synchronously via :meth:`pump_once` (tests, benches
    that want deterministic interleaving)."""

    def __init__(self, wal=None, *, wal_dir: Optional[str] = None,
                 ckpt_dir: Optional[str] = None,
                 leader_tick: Optional[Callable[[], int]] = None,
                 poll_s: float = 0.002,
                 max_chunk_bytes: int = 1 << 20,
                 epoch: Optional[int] = None) -> None:
        if wal is None and wal_dir is None:
            raise ValueError("SegmentShipper needs a wal or a wal_dir")
        self.wal = wal
        #: explicit epoch override (cold-log mode); with a live wal the
        #: shipper reads ``wal.epoch`` at stamp time so a recovery-time
        #: ``adopt_epoch`` is picked up without re-wiring
        self._epoch = epoch
        self.wal_dir = wal_dir if wal_dir is not None else wal.wal_dir
        self.ckpt_dir = ckpt_dir
        self._leader_tick = leader_tick or (lambda: 0)
        self.poll_s = poll_s
        self.max_chunk_bytes = max(int(max_chunk_bytes), 1 << 10)
        self._lock = named_lock("wal.ship")
        self._followers: Dict[str, _FollowerState] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.bytes_total = 0
        self.shipments = 0
        self.nacks = 0
        self.crc_stops = 0
        #: NACKs that named a newer epoch — this shipper is fenced
        self.fence_nacks = 0
        #: bytes re-offered below a follower's high-water mark (the
        #: WAL-as-retransmit-buffer path, driven by real loss)
        self.retransmit_bytes = 0
        #: link-level no-progress passes (follower.receive() -> None)
        self.link_stalls = 0
        #: followers re-anchored because their cursor predated a
        #: compacted range (wal/compact.py) — the truncation re-anchor
        #: path extended to rewritten-in-place segments
        self.compact_reanchors = 0
        #: tile-unit bootstrap transfers: checkpoint files shipped as
        #: independently CRC-framed units (REFLOW_TILE_BYTES > 0 and a
        #: follower exposing receive_ckpt_tile) — a NACK re-fetches one
        #: tile, not the chain
        self.tile_units_shipped = 0
        self.tile_unit_retries = 0
        self.tile_bootstraps = 0
        #: (mtime_ns, {out_seq: entry}) cache of the compaction
        #: manifest so the hot shipping path stats instead of parsing
        self._compact_cache: Tuple[Optional[int], Dict[int, dict]] = \
            (None, {})
        #: (registry, prefix) pairs, unregistered from the *same*
        #: registry they were registered on (a bare prefix list silently
        #: leaked gauges on any non-global registry)
        self._metric_names: List[Tuple[object, str]] = []
        self._metrics_registry = None

    @property
    def epoch(self) -> int:
        """The epoch stamped into every outgoing shipment."""
        if self._epoch is not None:
            return self._epoch
        return self.wal.epoch if self.wal is not None else 0

    # -- membership --------------------------------------------------------

    def attach(self, follower, name: Optional[str] = None) -> str:
        """Register ``follower`` and run the watermark handshake:
        ``subscribe()`` for its persisted cursor, falling back to a
        checkpoint-anchored ``bootstrap(ckpt_dir)`` (or the oldest
        on-disk segment) for a fresh replica."""
        name = name or getattr(follower, "name", None) \
            or f"follower-{len(self._followers)}"
        st = _FollowerState(name, follower)
        cursor = follower.subscribe()
        if cursor is None:
            cursor = self._bootstrap(st)
        st.cursor = LogPosition(*cursor)
        with self._lock:
            if name in self._followers:
                raise ValueError(f"follower {name!r} already attached")
            self._followers[name] = st
        if self._metrics_registry is not None \
                and hasattr(follower, "conn_state"):
            self._publish_conn_state(self._metrics_registry, name)
        return name

    def detach(self, name: str) -> None:
        with self._lock:
            self._followers.pop(name, None)

    def _bootstrap(self, st: _FollowerState) -> Tuple[int, int]:
        from reflow_tpu.utils.checkpoint import checkpoint_exists

        st.bootstraps += 1
        # the re-anchor point is a segment start established *now*:
        # remember the compaction generation it was minted under so a
        # later rewrite of that segment invalidates the cursor again
        st.anchor_gen = self._compact_gen()
        if self.ckpt_dir is not None and checkpoint_exists(self.ckpt_dir):
            if env_int("REFLOW_TILE_BYTES") > 0 \
                    and hasattr(st.follower, "receive_ckpt_tile"):
                cursor = self._bootstrap_tiles(st)
                if cursor is not None:
                    return cursor
                # exhausted retries or a mid-transfer surprise: the
                # plain whole-directory bootstrap is always correct
            return tuple(st.follower.bootstrap(self.ckpt_dir))
        segs = list_segments(self.wal_dir)
        first = segs[0][0] if segs else 0
        return (first, len(_MAGIC))

    def _bootstrap_tiles(self,
                         st: _FollowerState) -> Optional[Tuple[int, int]]:
        """Ship the checkpoint directory file-by-file as independently
        CRC-framed units (``reflow.tile_ship/1``): each tile file of a
        tiled checkpoint travels alone, so a NACK re-fetches one tile
        instead of the whole chain. ``meta.pkl`` is deliberately sent
        last — it names every tile file, so a torn transfer can never
        look complete to the receiver. Returns the follower's anchored
        cursor, or None to fall back to the plain bootstrap."""
        try:
            files = []
            for root, _dirs, names in os.walk(self.ckpt_dir):
                for n in sorted(names):
                    if n.endswith(".tmp"):
                        continue
                    p = os.path.join(root, n)
                    files.append((os.path.relpath(p, self.ckpt_dir), p))
        except OSError:
            return None
        if not files:
            return None
        files.sort(key=lambda fp: (fp[0] == "meta.pkl", fp[0]))
        retries = max(1, env_int("REFLOW_TILE_SHIP_RETRIES"))
        total = len(files)
        cursor = None
        for i, (rel, path) in enumerate(files):
            try:
                with open(path, "rb") as f:
                    payload = f.read()
            except OSError:
                # the chain rotated under us (a reaped tile file):
                # this transfer is stale, start over via the fallback
                return None
            unit = {"schema": "reflow.tile_ship/1",
                    "rel": rel.replace(os.sep, "/"), "idx": i,
                    "total": total, "payload": payload,
                    "crc": zlib.crc32(payload) & 0xFFFFFFFF,
                    "last": i == total - 1}
            ok = False
            for attempt in range(retries):
                t0 = time.perf_counter()
                try:
                    resp = st.follower.receive_ckpt_tile(unit)
                except Exception:  # noqa: BLE001 - transport-level miss
                    resp = None
                accepted = bool(resp) and bool(resp.get("ok"))
                if _trace.ENABLED:
                    _trace.evt("tile_ship", t0,
                               time.perf_counter() - t0,
                               track="wal-shipper",
                               args={"follower": st.name, "rel": unit["rel"],
                                     "idx": i, "total": total,
                                     "bytes": len(payload),
                                     "attempt": attempt,
                                     "ok": accepted})
                if accepted:
                    ok = True
                    self.tile_units_shipped += 1
                    if unit["last"]:
                        cursor = resp.get("cursor")
                    break
                self.tile_unit_retries += 1
            if not ok:
                return None
        if cursor is None:
            return None
        self.tile_bootstraps += 1
        return tuple(cursor)

    # -- shipping ----------------------------------------------------------

    def _horizon(self) -> LogPosition:
        if self.wal is not None:
            return self.wal.synced_position()
        # cold log: everything on disk is the shippable prefix
        segs = list_segments(self.wal_dir)
        if not segs:
            return LogPosition(0, len(_MAGIC))
        seq, path = segs[-1]
        return LogPosition(seq, os.path.getsize(path))

    def pump_once(self) -> int:
        """Ship every follower as far toward the current synced
        watermark as one pass allows. Returns bytes shipped."""
        horizon = self._horizon()
        with self._lock:
            states = list(self._followers.values())
        shipped = 0
        for st in states:
            shipped += self._ship_follower(st, horizon)
        if shipped or states:
            self._persist_state(horizon)
        return shipped

    def _ship_follower(self, st: _FollowerState,
                       horizon: LogPosition) -> int:
        base = st.bytes_total
        guard = 0
        while (not st.fenced and st.cursor is not None
               and st.cursor < horizon):
            guard += 1
            if guard > 10_000:  # paranoia: never wedge the pump loop
                break
            if not self._ship_chunk(st, horizon):
                break
        return st.bytes_total - base

    def _ship_chunk(self, st: _FollowerState,
                    horizon: LogPosition) -> bool:
        """Read, re-verify and send one chunk ``[cursor, ...)``; returns
        False when this follower can make no more progress this pass."""
        segs = dict(list_segments(self.wal_dir))
        cur = st.cursor
        if cur.segment not in segs:
            # the leader truncated past this follower's cursor (a
            # checkpoint retired those segments) — re-anchor on the
            # checkpoint instead of a full refetch. Compaction reuses
            # this path for unlinked middle segments of a folded range.
            st.cursor = LogPosition(*self._bootstrap(st))
            return st.cursor != cur
        ent = self._compact_entries().get(cur.segment)
        if (ent is not None and ent["gen"] > st.anchor_gen
                and cur.offset > len(_MAGIC)):
            # the segment under this mid-segment cursor was rewritten
            # by a compaction pass from a newer generation: the offset
            # addresses bytes of the old era. Partially folded replay
            # would break the all-or-nothing batch-id dedup, so
            # re-anchor on the checkpoint — the same contract as a
            # truncation, through the same bootstrap.
            st.compact_reanchors += 1
            self.compact_reanchors += 1
            st.cursor = LogPosition(*self._bootstrap(st))
            return st.cursor != cur
        sealed = cur.segment < horizon.segment
        if sealed:
            end = os.path.getsize(segs[cur.segment])
        else:
            end = horizon.offset
        if end <= cur.offset:
            if not sealed:
                return False
            # fully shipped sealed segment with no remaining frames to
            # piggyback the seal on: the seal must still travel as a
            # normal (empty) shipment — the receiver's cursor is the
            # authoritative one, and a shipper-local hop would strand
            # it at the old segment's end, NACK-rejecting every later
            # chunk forever (cursor livelock)
            payload = b""
            chunk_end = cur.offset
            entries = []
        else:
            with open(segs[cur.segment], "rb") as f:
                f.seek(cur.offset)
                want = min(end - cur.offset, self.max_chunk_bytes)
                data = f.read(want)
            entries, valid, reason = iter_frames(data, cur.segment,
                                                 cur.offset)
            if valid < len(data) and len(data) < end - cur.offset:
                # chunk boundary split a frame mid-air: ship the whole
                # frames we have, the next chunk restarts at the boundary
                reason = None
            if valid == 0:
                if reason is not None and sealed:
                    # before declaring corruption, re-read the
                    # compaction manifest uncached: a pass may have
                    # swapped the folded file under our feet between
                    # the manifest check and the read above
                    ent = self._compact_entries(force=True) \
                        .get(cur.segment)
                    if ent is not None and ent["gen"] > st.anchor_gen:
                        st.compact_reanchors += 1
                        self.compact_reanchors += 1
                        st.cursor = LogPosition(*self._bootstrap(st))
                        return st.cursor != cur
                    raise WalError(
                        f"wal-{cur.segment:08d}.log @ {cur.offset}: "
                        f"{reason} in a sealed segment below the synced "
                        f"watermark — real corruption, refusing to ship")
                self.crc_stops += 1
                return False
            payload = data[:valid]
            chunk_end = cur.offset + valid
        seals = sealed and chunk_end == end
        nxt = self._next_segment(segs, cur.segment) if seals else None
        tok: Optional[str] = None
        causes: List[str] = []
        if _trace.ENABLED:
            # stamp a causality token so this chunk's ship_segment /
            # net_send / replica_replay spans stitch across processes;
            # lazy import — obs.wire rides net/, which rides this module
            from reflow_tpu.obs.wire import node_id as _node_id
            tok = _trace.mint_cause(_node_id(), self.epoch)
            # per-write tokens stamped on the chunk's WAL records: the
            # span carries BOTH, joining each sampled write's chain to
            # the chunk-level ship/send/replay spans
            for _p, _e, r in entries:
                for c in record_causes(r):
                    if c not in causes:
                        causes.append(c)
        shipment = Shipment(cur.segment, cur.offset, payload, chunk_end,
                            seals, nxt, self._leader_tick(), self.epoch,
                            tok)
        if payload and st.high_water is not None and cur < st.high_water:
            # re-offering bytes the follower was already sent: the WAL
            # acting as the retransmit buffer, made visible
            st.retransmit_bytes += len(payload)
            self.retransmit_bytes += len(payload)
        offered = LogPosition(cur.segment, chunk_end)
        if st.high_water is None or offered > st.high_water:
            st.high_water = offered
        t0 = time.perf_counter()
        resp = st.follower.receive(shipment)
        if _trace.ENABLED:
            _trace.evt("ship_segment", t0, time.perf_counter() - t0,
                       track="wal-shipper",
                       args={"follower": st.name,
                             "segment": cur.segment,
                             "offset": cur.offset,
                             "bytes": len(payload),
                             "seals": seals,
                             "cause": tok,
                             "causes": causes,
                             "ack": isinstance(resp, ShipAck)})
        if resp is None:
            # link-level no-progress (remote follower down or inside a
            # backoff window): skip this follower for the pass. Not a
            # NACK — the replica never spoke.
            st.link_stalls += 1
            self.link_stalls += 1
            return False
        if isinstance(resp, ShipAck):
            st.cursor = LogPosition(*resp.cursor)
            st.applied_horizon = resp.horizon
            st.bytes_total += len(payload)
            st.shipments += 1
            self.bytes_total += len(payload)
            self.shipments += 1
            return True
        # NACK: adopt the receiver's authoritative cursor and let the
        # next pass re-read from disk (the WAL is the retransmit buffer)
        st.nacks += 1
        self.nacks += 1
        if resp.reason.startswith("fenced"):
            # the receiver is on a newer epoch: we are the zombie. Do
            # NOT adopt its cursor — our log diverged at the promotion
            # horizon; just stop offering this follower anything.
            st.fenced = True
            self.fence_nacks += 1
            return False
        if resp.cursor is not None:
            st.cursor = LogPosition(*resp.cursor)
        return False

    @staticmethod
    def _next_segment(segs: Dict[int, str], seq: int) -> int:
        later = [s for s in segs if s > seq]
        return min(later) if later else seq + 1

    # -- compaction awareness ----------------------------------------------

    def _compact_entries(self, force: bool = False) -> Dict[int, dict]:
        """``{out_segment: manifest entry}`` for the leader log's
        compacted ranges, cached by manifest mtime (flips are atomic,
        so mtime-staleness is the only hazard and ``force`` closes it
        on the one path that matters)."""
        path = os.path.join(self.wal_dir, COMPACT_MANIFEST_FILE)
        try:
            mtime = os.stat(path).st_mtime_ns
        except OSError:
            self._compact_cache = (None, {})
            return {}
        cached_key, cached = self._compact_cache
        if not force and cached_key == mtime:
            return cached
        manifest = read_compact_manifest(self.wal_dir) or {}
        entries = {e["out"]: e for e in manifest.get("ranges", [])}
        self._compact_cache = (mtime, entries)
        return entries

    def _compact_gen(self) -> int:
        """The current compaction generation (0 = never compacted)."""
        entries = self._compact_entries()
        return max((e["gen"] for e in entries.values()), default=0)

    def min_cursor(self) -> Optional[LogPosition]:
        """The laggiest attached, unfenced follower's cursor — the
        compactor's eligibility floor: segments at or past it are still
        being fetched and must not be rewritten under a live cursor."""
        with self._lock:
            cursors = [st.cursor for st in self._followers.values()
                       if not st.fenced and st.cursor is not None]
        return min(cursors) if cursors else None

    # -- backlog / state ---------------------------------------------------

    def fully_shipped(self, horizon: Optional[LogPosition] = None) -> bool:
        """True when every attached, unfenced follower's cursor has
        reached ``horizon`` (default: the current synced watermark).
        The patient-drain predicate: a remote follower mid-backoff
        reports no progress for whole passes, so a drain loop must ask
        'is everyone there yet' instead of 'did this pass move bytes'."""
        if horizon is None:
            horizon = self._horizon()
        with self._lock:
            states = list(self._followers.values())
        return all(st.fenced or (st.cursor is not None
                                 and st.cursor >= horizon)
                   for st in states)

    def backlog_segments(self) -> int:
        """How many segments the laggiest follower still has to fetch
        (0 = everyone is inside the watermark segment)."""
        horizon = self._horizon()
        with self._lock:
            cursors = [st.cursor for st in self._followers.values()
                       if st.cursor is not None]
        if not cursors:
            return 0
        return max(0, horizon.segment - min(c.segment for c in cursors))

    def _transport_state(self, st: _FollowerState) -> Optional[dict]:
        """Connection-level state for one follower: the client's
        reconnect-policy snapshot plus shipper-side retransmit/stall
        counters. None for in-process followers (no wire, no story)."""
        snap_fn = getattr(st.follower, "transport_snapshot", None)
        if snap_fn is None:
            return None
        try:
            snap = dict(snap_fn())
        except Exception:  # noqa: BLE001 - advisory state only
            snap = {"state": "unknown"}
        snap["retransmit_bytes"] = st.retransmit_bytes
        snap["link_stalls"] = st.link_stalls
        return snap

    def _persist_state(self, horizon: LogPosition) -> None:
        with self._lock:
            followers = {}
            transport = {}
            for st in self._followers.values():
                followers[st.name] = {
                    "shipped": list(st.cursor) if st.cursor else None,
                    "applied_horizon": st.applied_horizon,
                    "bytes_total": st.bytes_total,
                    "shipments": st.shipments,
                    "nacks": st.nacks,
                    "bootstraps": st.bootstraps,
                    "compact_reanchors": st.compact_reanchors,
                }
                tsnap = self._transport_state(st)
                if tsnap is not None:
                    transport[st.name] = tsnap
        state = {
            "schema": SHIP_STATE_SCHEMA,
            "horizon": list(horizon),
            "leader_tick": self._leader_tick(),
            "bytes_total": self.bytes_total,
            "shipments": self.shipments,
            "nacks": self.nacks,
            "retransmit_bytes": self.retransmit_bytes,
            "link_stalls": self.link_stalls,
            "tile_units_shipped": self.tile_units_shipped,
            "tile_unit_retries": self.tile_unit_retries,
            "tile_bootstraps": self.tile_bootstraps,
            "followers": followers,
        }
        if transport:
            state["transport"] = transport
        path = os.path.join(self.wal_dir, SHIP_STATE_FILE)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(state, f, indent=2, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass  # tooling state only; never fail shipping over it

    # -- thread loop -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="wal-shipper", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                moved = self.pump_once()
            except WalError:
                raise
            except Exception:  # noqa: BLE001 - a dying follower must
                moved = 0      # not take the shipping loop with it
            if not moved:
                self._stop.wait(self.poll_s)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def close(self) -> None:
        self.stop()
        for reg, name in self._metric_names:
            reg.unregister_prefix(name)
        self._metric_names.clear()

    # -- observability -----------------------------------------------------

    def _net_reconnects_total(self) -> int:
        with self._lock:
            states = list(self._followers.values())
        return sum(getattr(st.follower, "reconnects_total", 0)
                   for st in states)

    def _conn_state(self, name: str) -> str:
        with self._lock:
            st = self._followers.get(name)
        if st is None:
            return "detached"
        return getattr(st.follower, "conn_state", "local")

    def publish_metrics(self, registry=None, name: str = "ship") -> None:
        reg = registry if registry is not None else REGISTRY
        self._metrics_registry = reg
        reg.gauge(f"{name}.bytes_total", lambda: self.bytes_total)
        reg.gauge(f"{name}.backlog_segments", self.backlog_segments)
        reg.gauge(f"{name}.shipments", lambda: self.shipments)
        reg.gauge(f"{name}.nacks", lambda: self.nacks)
        reg.gauge(f"{name}.followers", lambda: len(self._followers))
        reg.gauge(f"{name}.link_stalls", lambda: self.link_stalls)
        reg.gauge(f"{name}.compact_reanchors",
                  lambda: self.compact_reanchors)
        reg.gauge(f"{name}.tile_units_shipped",
                  lambda: self.tile_units_shipped)
        reg.gauge(f"{name}.tile_bootstraps",
                  lambda: self.tile_bootstraps)
        reg.gauge("net.reconnects_total", self._net_reconnects_total)
        reg.gauge("net.retransmit_bytes", lambda: self.retransmit_bytes)
        self._metric_names.append((reg, name))
        self._metric_names.append((reg, "net."))
        with self._lock:
            states = list(self._followers.values())
        for st in states:
            if hasattr(st.follower, "conn_state"):
                self._publish_conn_state(reg, st.name)

    def _publish_conn_state(self, reg, follower_name: str) -> None:
        gname = f"replica.{follower_name}.conn_state"
        reg.gauge(gname, lambda n=follower_name: self._conn_state(n))
        self._metric_names.append((reg, gname))
