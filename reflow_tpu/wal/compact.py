"""Key-level WAL compaction: fold sealed segments down to O(state).

A long-lived leader's replay tail holds every update since the last
checkpoint anchor — N updates to one key cost N records on every
recovery and every replica bootstrap. :class:`WalCompactor` rewrites a
range of **sealed, fully-shipped** segments at or after the newest
checkpoint anchor so that all push records fold key-level: per source,
the (key, value) rows of the whole range are summed into one columnar
batch (zero-weight rows — insert-then-retract — disappear entirely),
while every original batch id is carried forward on the folded record
and every tick marker / epoch stamp is preserved verbatim. The result
replays through the unchanged ``recover()``/``replay_records`` path to
**exact state parity** with the original range (same final views, same
tick counter, same dedup window) in O(state) work instead of
O(history).

Atomicity (write-new → fsync → manifest flip → unlink):

1. the folded range ``[a..b]`` is written to ``wal-<a>.log.compact``
   and fsynced;
2. ``compact-manifest.json`` flips atomically to record the range
   (out segment, covered seqs, generation) — the advisory commit point
   shippers and ``wal_inspect`` read;
3. ``os.replace`` swaps the compacted file over segment ``a``;
4. the superseded originals ``a+1..b`` are unlinked.

A crash anywhere in between leaves a *replay-equivalent* log: the
folded segment carries the batch ids of everything it covers, so any
surviving original records dedup away during replay — double-apply is
impossible by the same mechanism that makes recovery idempotent.
Interrupted passes are rolled forward (or back) on the next pass.

Followers: a cursor inside a compacted range points at bytes that no
longer exist. Deleted middle segments hit ``SegmentShipper``'s existing
leader-truncation re-anchor; for the rewritten *first* segment the
shipper consults the manifest generation and re-anchors any cursor
established under an older generation (``wal/ship.py``). Eligibility
already excludes segments any *attached* follower still needs, so only
detached/stale followers ever take that path — and re-anchoring is
O(state) now, which is the point.

Run it from the :class:`~reflow_tpu.serve.control.ControlPlane`
(``compactor=``): the control loop supervises the compactor thread with
the same respawn-or-fail-fast budget as the WAL committer.

**Tiled folds** (``REFLOW_TILE_BYTES`` > 0, docs/guide.md 'Tiled
maintenance'): the monolithic fold holds the whole folded state of the
range in RAM. Above the tile budget the pass instead runs a cheap
key-histogram scan, plans contiguous key-range tiles under the budget
(:mod:`reflow_tpu.utils.tiles`), and folds one [key-range] x
[segment-range] tile at a time — peak resident fold state is one tile.
The output segment holds, per source, one zero-row *cover* record
carrying every original batch id (written first, so a restore point
inside the fold fails loud before any part applies) and one *part*
record per tile with a synthetic batch id ``<first_id>#t<k>``; replay
dedup works unchanged. Tiles append incrementally to the same tmp
file; a ``<tmp>.progress`` sidecar flips after each tile so a crash
mid-pass resumes without refolding finished tiles (per-tile
generations record which pass attempt folded each tile).
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from reflow_tpu.utils.runtime import named_lock
from reflow_tpu.obs.registry import REGISTRY
from reflow_tpu.wal.log import (_HEADER, _MAGIC, WalError,
                                _read_segment, _seg_path, list_segments)

__all__ = ["WalCompactor", "read_compact_manifest",
           "COMPACT_MANIFEST_FILE", "COMPACT_SCHEMA"]

COMPACT_MANIFEST_FILE = "compact-manifest.json"
COMPACT_SCHEMA = "reflow.wal_compact/1"
PROGRESS_SCHEMA = "reflow.wal_compact_progress/1"
_TMP_SUFFIX = ".compact"
_PROGRESS_SUFFIX = ".compact.progress"


def read_compact_manifest(wal_dir: str) -> Optional[dict]:
    """The compaction manifest as a dict, or None when the log was
    never compacted. Tolerates a missing file, fails loud on corrupt
    JSON (flips are atomic; garbage means real trouble)."""
    path = os.path.join(wal_dir, COMPACT_MANIFEST_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _scalarize(x):
    """A hashable identity for one key or value cell (ndarray cells
    hash by dtype/shape/bytes)."""
    import numpy as np

    if isinstance(x, np.ndarray):
        if x.ndim == 0:
            return x.item()
        return (x.dtype.str, x.shape, x.tobytes())
    if isinstance(x, np.generic):
        return x.item()
    return x


def _col(cells: List, like) -> "object":
    """Rebuild one columnar array from folded cells, matching the dtype
    and row shape of ``like`` (a column from an original record)."""
    import numpy as np

    arr_like = np.asarray(like)
    if arr_like.dtype == object:
        out = np.empty(len(cells), dtype=object)
        out[:] = cells
        return out
    if not cells:
        return np.empty((0,) + arr_like.shape[1:], dtype=arr_like.dtype)
    return np.asarray(cells, dtype=arr_like.dtype)


class _SourceFold:
    """Running key-level fold of one source's push records."""

    __slots__ = ("nid", "name", "first_tick", "epoch", "agg", "ids",
                 "ids_set", "keys_like", "values_like")

    def __init__(self, nid: int, rec: Dict):
        self.nid = nid
        self.name = rec["node_name"]
        self.first_tick = rec.get("tick", 0)
        self.epoch = 0
        #: rowkey -> [key_cell, value_cell, weight]
        self.agg: Dict = {}
        self.ids: List[str] = []
        self.ids_set = set()
        self.keys_like = rec["keys"]
        self.values_like = rec["values"]

    def add(self, rec: Dict, row_filter=None, take_ids: bool = True,
            take_rows: bool = True) -> None:
        """Fold one push record in. A tiled pass restricts the fold:
        ``row_filter(key) -> bool`` keeps only the tile's rows,
        ``take_ids=False`` leaves batch ids to the cover record, and
        ``take_rows=False`` (histogram/cover pass) collects only
        ids/epoch/tick."""
        import numpy as np

        self.epoch = max(self.epoch, rec.get("epoch", 0) or 0)
        if take_ids:
            ids = rec.get("batch_ids")
            if ids is None:
                ids = [rec["batch_id"]] if rec.get("batch_id") else []
            for b in ids:
                if b not in self.ids_set:
                    self.ids_set.add(b)
                    self.ids.append(b)
        if not take_rows:
            return
        keys = np.asarray(rec["keys"])
        values = np.asarray(rec["values"])
        weights = np.asarray(rec["weights"])
        for k, v, w in zip(keys, values, weights):
            if row_filter is not None and not row_filter(k):
                continue
            rk = (_scalarize(k), _scalarize(v))
            cell = self.agg.get(rk)
            if cell is None:
                self.agg[rk] = [k, v, int(w)]
            else:
                cell[2] += int(w)

    def resident_bytes(self) -> int:
        """Approximate host bytes this fold holds resident — the
        quantity the tile budget bounds (``compact.peak_tile_bytes``)."""
        from reflow_tpu.utils.tiles import approx_row_bytes

        return sum(approx_row_bytes(c[0], c[1])
                   for c in self.agg.values())

    def record(self, batch_id: Optional[str] = None) -> Dict:
        """The folded record. ``batch_id`` overrides for a tile *part*:
        the record then carries only that synthetic id (dedup unit =
        one tile) and the original ids ride the range's cover record."""
        rows = [c for c in self.agg.values() if c[2] != 0]
        rec = {
            "kind": "push",
            "tick": self.first_tick,
            "node": self.nid,
            "node_name": self.name,
            "batch_id": batch_id if batch_id is not None else self.ids[0],
            # the folded batch is a SUM with no per-id slice; replay
            # fails loud if a restore point falls inside the fold
            # (wal/recovery.py's partial-ids check keys off this)
            "compacted": True,
            "keys": _col([c[0] for c in rows], self.keys_like),
            "values": _col([c[1] for c in rows], self.values_like),
            "weights": _col([c[2] for c in rows], [0]),
        }
        if batch_id is None and len(self.ids) > 1:
            rec["batch_ids"] = list(self.ids)
        if self.epoch:
            rec["epoch"] = self.epoch
        return rec


class WalCompactor:
    """Background key-level compactor over one leader WAL directory.

    ``wal`` is the live :class:`~reflow_tpu.wal.log.WriteAheadLog`
    (or None for a cold log — pass ``wal_dir``; tools, benches and
    recovery-time catch-up compaction). ``shipper`` (optional) bounds
    eligibility to segments every attached follower has fully fetched.
    ``ckpt_dir`` (optional) supplies the newest checkpoint anchor — a
    :class:`~reflow_tpu.utils.checkpoint.CheckpointChain` root or a
    legacy full checkpoint — and compaction never folds across it
    (records before the anchor belong to the checkpoint, records after
    it to the replay tail; a fold spanning the boundary would move tail
    records below the recovery scan start).

    Drive it with the background thread (``start()``/``stop()``,
    supervised by the ControlPlane) or synchronously via
    :meth:`compact_once`."""

    def __init__(self, wal=None, *, wal_dir: Optional[str] = None,
                 shipper=None, ckpt_dir: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 min_segments: Optional[int] = None,
                 keep_segments: Optional[int] = None,
                 tile_bytes: Optional[int] = None,
                 crash=None) -> None:
        from reflow_tpu.utils.config import env_float, env_int

        if wal is None and wal_dir is None:
            raise ValueError("WalCompactor needs a wal or a wal_dir")
        self.wal = wal
        self.wal_dir = wal_dir if wal_dir is not None else wal.wal_dir
        self.shipper = shipper
        self.ckpt_dir = ckpt_dir
        self.interval_s = (interval_s if interval_s is not None
                           else env_float("REFLOW_COMPACT_INTERVAL_S"))
        self.min_segments = (min_segments if min_segments is not None
                             else env_int("REFLOW_COMPACT_MIN_SEGMENTS"))
        self.keep_segments = (keep_segments if keep_segments is not None
                              else env_int("REFLOW_COMPACT_KEEP_SEGMENTS"))
        self.tile_bytes = (tile_bytes if tile_bytes is not None
                           else env_int("REFLOW_TILE_BYTES"))
        self._crash = crash
        self._lock = named_lock("wal.compact")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.passes = 0
        self.folds = 0
        self.segments_folded = 0
        self.records_in = 0
        self.records_out = 0
        self.reclaimed_bytes = 0
        self.tile_folds = 0
        self.peak_tile_bytes = 0
        self.restarts = 0
        self.last_error: Optional[BaseException] = None
        self._events: List[Dict] = []
        self._metric_names: List[Tuple[object, str]] = []

    def _crash_point(self, name: str) -> None:
        if self._crash is not None:
            self._crash.point(name)

    # -- eligibility -------------------------------------------------------

    def _anchor_segment(self) -> Optional[int]:
        """Segment of the newest checkpoint anchor (chain head or
        legacy full), or None when no checkpoint exists."""
        if self.ckpt_dir is None:
            return None
        from reflow_tpu.utils.checkpoint import chain_head_wal_pos

        pos = chain_head_wal_pos(self.ckpt_dir)
        if pos is None:
            meta_path = os.path.join(self.ckpt_dir, "meta.pkl")
            if os.path.exists(meta_path):
                import pickle

                with open(meta_path, "rb") as f:
                    pos = pickle.load(f).get("wal_pos")
        if pos is None:
            return None
        seg, off = pos
        # anchors are segment starts by construction (saves rotate
        # first); a mid-segment anchor would mean folding could bury
        # post-anchor bytes, so exclude that segment entirely
        return seg if off <= len(_MAGIC) else seg + 1

    def eligible_range(self) -> Optional[List[int]]:
        """The segment seqs the next pass would fold, or None."""
        segs = list_segments(self.wal_dir)
        if not segs:
            return None
        seqs = [s for s, _ in segs]
        if self.wal is not None:
            sealed_lim = self.wal.synced_position().segment
        else:
            sealed_lim = seqs[-1]  # cold log: all but the newest file
        lo = self._anchor_segment()
        lo = seqs[0] if lo is None else max(lo, seqs[0])
        floor = None
        if self.shipper is not None:
            mc = self.shipper.min_cursor()
            if mc is not None:
                floor = mc.segment
        cand = [s for s in seqs
                if lo <= s < sealed_lim
                and (floor is None or s < floor)]
        if self.keep_segments > 0:
            cand = cand[:max(0, len(cand) - self.keep_segments)]
        if not cand:
            return None
        manifest = read_compact_manifest(self.wal_dir)
        covered_hi = -1
        if manifest is not None:
            for ent in manifest.get("ranges", []):
                if ent["out"] == cand[0]:
                    covered_hi = ent["covers"][1]
        fresh = [s for s in cand if s > covered_hi]
        if len(fresh) < max(1, self.min_segments):
            return None
        return cand

    def reclaimable_bytes(self) -> int:
        """Bytes the next pass could fold (sizes of the eligible
        segments) — drops to ~one folded segment after a pass, which is
        the bounded-footprint signal the bench asserts on."""
        rng = self.eligible_range()
        if not rng:
            return 0
        segs = dict(list_segments(self.wal_dir))
        return sum(os.path.getsize(segs[s]) for s in rng if s in segs)

    def log_bytes(self) -> int:
        return sum(os.path.getsize(p)
                   for _s, p in list_segments(self.wal_dir))

    # -- the pass ----------------------------------------------------------

    def compact_once(self) -> Optional[Dict]:
        """One full pass: finish any interrupted pass, then fold the
        eligible range (if any). Returns the pass event dict or None
        when there was nothing to do."""
        self.passes += 1
        try:
            self._recover_interrupted()
            rng = self.eligible_range()
            if not rng:
                return None
            return self._fold_range(rng)
        except FileNotFoundError:
            # a checkpoint truncation raced the pass and deleted a
            # candidate out from under us; next pass sees fresh state
            return None

    def _fold_range(self, rng: List[int]) -> Optional[Dict]:
        if self.tile_bytes and self.tile_bytes > 0:
            return self._fold_range_tiled(rng)
        return self._fold_range_mono(rng)

    def _fold_range_mono(self, rng: List[int]) -> Optional[Dict]:
        segs = dict(list_segments(self.wal_dir))
        folds: Dict[int, _SourceFold] = {}
        order: List[int] = []
        passthrough: List[Dict] = []
        records_in = 0
        orig_bytes = 0
        tick_lo: Optional[int] = None
        tick_hi: Optional[int] = None
        for seq in rng:
            path = segs[seq]
            orig_bytes += os.path.getsize(path)
            seg_records, _torn = _read_segment(path, seq, False)
            for _pos, rec in seg_records:
                records_in += 1
                kind = rec.get("kind")
                if kind == "push":
                    nid = rec["node"]
                    f = folds.get(nid)
                    if f is None:
                        f = folds[nid] = _SourceFold(nid, rec)
                        order.append(nid)
                    f.add(rec)
                elif kind == "tick":
                    t = rec.get("tick", 0)
                    tick_lo = t if tick_lo is None else min(tick_lo, t)
                    tick_hi = t if tick_hi is None else max(tick_hi, t)
                    passthrough.append(rec)
                elif kind == "ckpt":
                    # informational for replay, but wal_inspect
                    # discovers chain roots from the recorded paths —
                    # keep them (they are tiny)
                    passthrough.append(rec)
                else:
                    # unknown kinds survive verbatim (replay skips
                    # them; a future consumer must treat them as
                    # idempotent, same as the crash-window duplicates)
                    passthrough.append(rec)
        out_records = [folds[nid].record() for nid in order
                       if folds[nid].ids]
        out_records.extend(passthrough)
        out_seq = rng[0]
        tmp = _seg_path(self.wal_dir, out_seq) + _TMP_SUFFIX
        new_bytes = self._write_segment(tmp, out_records)
        return self._commit(rng, segs, tmp, new_bytes, orig_bytes,
                            records_in, len(out_records),
                            tick_lo, tick_hi, None)

    # -- tiled fold (REFLOW_TILE_BYTES > 0) --------------------------------

    def _fold_range_tiled(self, rng: List[int]) -> Optional[Dict]:
        """Fold the range one key-range tile at a time: histogram pass
        -> tile plan -> per-tile fold passes appending to the same tmp
        segment, with a progress sidecar flipped after every tile so an
        interrupted pass resumes without refolding finished tiles."""
        import time

        import numpy as np

        from reflow_tpu.obs import trace as _trace
        from reflow_tpu.utils import tiles as _t

        budget = int(self.tile_bytes)
        segs = dict(list_segments(self.wal_dir))
        # -- histogram pass: per-bucket byte estimate, cover folds
        # (ids/epoch/tick only — no rows held), passthrough, stats
        bucket_bytes = [0.0] * _t.N_BUCKETS
        covers: Dict[int, _SourceFold] = {}
        order: List[int] = []
        passthrough: List[Dict] = []
        records_in = 0
        orig_bytes = 0
        tick_lo: Optional[int] = None
        tick_hi: Optional[int] = None
        for seq in rng:
            path = segs[seq]
            orig_bytes += os.path.getsize(path)
            seg_records, _torn = _read_segment(path, seq, False)
            for _pos, rec in seg_records:
                records_in += 1
                kind = rec.get("kind")
                if kind == "push":
                    nid = rec["node"]
                    f = covers.get(nid)
                    if f is None:
                        f = covers[nid] = _SourceFold(nid, rec)
                        order.append(nid)
                    f.add(rec, take_rows=False)
                    for k, v in zip(np.asarray(rec["keys"]),
                                    np.asarray(rec["values"])):
                        bucket_bytes[_t.bucket_of(k)] += \
                            _t.approx_row_bytes(k, v)
                elif kind == "tick":
                    t = rec.get("tick", 0)
                    tick_lo = t if tick_lo is None else min(tick_lo, t)
                    tick_hi = t if tick_hi is None else max(tick_hi, t)
                    passthrough.append(rec)
                else:
                    passthrough.append(rec)
        plan = [[lo, hi] for lo, hi in _t.plan_tiles(bucket_bytes, budget)]
        if len(plan) <= 1:
            # state fits one tile: the monolithic fold is the same
            # work without synthetic ids or a sidecar
            return self._fold_range_mono(rng)
        out_seq = rng[0]
        tmp = _seg_path(self.wal_dir, out_seq) + _TMP_SUFFIX
        prog_path = _seg_path(self.wal_dir, out_seq) + _PROGRESS_SUFFIX
        cover_recs = [covers[nid].record() for nid in order
                      if covers[nid].ids]
        # -- resume or start: a valid sidecar for this exact range and
        # plan means finished tiles are already on the tmp segment
        prog = self._read_progress(prog_path)
        if not (prog is not None and os.path.exists(tmp)
                and prog.get("covers") == [rng[0], rng[-1]]
                and prog.get("plan") == plan):
            for stale in (tmp, prog_path):
                if os.path.exists(stale):
                    os.remove(stale)
            end = self._append_records(tmp, cover_recs, None)
            prog = {"schema": PROGRESS_SCHEMA, "covers": [rng[0], rng[-1]],
                    "plan": plan, "budget": budget, "attempt": 1,
                    "covers_end": end, "done": []}
            self._write_progress(prog_path, prog)
        else:
            prog["attempt"] = int(prog.get("attempt", 1)) + 1
        done = {int(d["tile"]): d for d in prog["done"]}
        end = max([int(prog["covers_end"])]
                  + [int(d["end"]) for d in done.values()])
        peak = max([0] + [int(d.get("resident", 0))
                          for d in done.values()])
        resumed_tiles = len(done)
        gens: List[int] = [0] * len(plan)
        for k, d in done.items():
            gens[k] = int(d["gen"])
        parts_out = sum(int(d.get("parts", 0)) for d in done.values())
        for k, (lo, hi) in enumerate(plan):
            if k in done:
                continue
            t0 = time.perf_counter()
            in_tile = (lambda key, _lo=lo, _hi=hi:
                       _lo <= _t.bucket_of(key) < _hi)
            folds: Dict[int, _SourceFold] = {}
            torder: List[int] = []
            for seq in rng:
                seg_records, _torn = _read_segment(segs[seq], seq, False)
                for _pos, rec in seg_records:
                    if rec.get("kind") != "push":
                        continue
                    nid = rec["node"]
                    f = folds.get(nid)
                    if f is None:
                        f = folds[nid] = _SourceFold(nid, rec)
                        torder.append(nid)
                    f.add(rec, row_filter=in_tile, take_ids=False)
            resident = sum(folds[nid].resident_bytes() for nid in torder)
            peak = max(peak, resident)
            recs = []
            for nid in torder:
                f = folds[nid]
                if any(c[2] != 0 for c in f.agg.values()):
                    recs.append(f.record(
                        batch_id=f"{covers[nid].ids[0]}#t{k}"))
            folds.clear()
            # append the tile (truncating any torn partial append from
            # a crashed attempt), then flip the sidecar: the tile is
            # durable before it is recorded done
            end = self._append_records(tmp, recs, end)
            parts_out += len(recs)
            self._crash_point("compact_tile_before_progress")
            gens[k] = prog["attempt"]
            prog["done"].append({"tile": k, "gen": prog["attempt"],
                                 "end": end, "resident": resident,
                                 "parts": len(recs)})
            self._write_progress(prog_path, prog)
            self._crash_point("compact_tile_after_progress")
            if _trace.ENABLED:
                _trace.evt("compact_tile", t0,
                           time.perf_counter() - t0,
                           track="wal-compactor",
                           args={"tile": k, "of": len(plan),
                                 "buckets": [lo, hi],
                                 "resident_bytes": resident,
                                 "parts": len(recs),
                                 "gen": prog["attempt"]})
            with self._lock:
                self.tile_folds += 1
                self.peak_tile_bytes = max(self.peak_tile_bytes,
                                           resident)
        new_bytes = self._append_records(tmp, passthrough, end)
        records_out = len(cover_recs) + parts_out + len(passthrough)
        tiles_info = {
            "n": len(plan),
            "budget": budget,
            "peak_tile_bytes": peak,
            "plan": plan,
            "gens": gens,
            "resumed_tiles": resumed_tiles,
            "attempts": prog["attempt"],
        }
        return self._commit(rng, segs, tmp, new_bytes, orig_bytes,
                            records_in, records_out, tick_lo, tick_hi,
                            tiles_info)

    def _commit(self, rng: List[int], segs: Dict[int, str], tmp: str,
                new_bytes: int, orig_bytes: int, records_in: int,
                records_out: int, tick_lo, tick_hi,
                tiles_info: Optional[Dict]) -> Optional[Dict]:
        """Shared commit tail: manifest flip -> swap -> unlink, with
        the crash seams every fold shape shares."""
        out_seq = rng[0]
        prog_path = _seg_path(self.wal_dir, out_seq) + _PROGRESS_SUFFIX
        self._crash_point("compact_before_flip")
        manifest = read_compact_manifest(self.wal_dir) or {
            "schema": COMPACT_SCHEMA, "gen": 0, "ranges": [],
            "reclaimed_bytes": 0}
        gen = manifest["gen"] + 1
        entry = {
            "out": out_seq,
            "covers": [rng[0], rng[-1]],
            "gen": gen,
            "bytes": new_bytes,
            "orig_bytes": orig_bytes,
            "records_in": records_in,
            "records_out": records_out,
            "tick_lo": tick_lo,
            "tick_hi": tick_hi,
        }
        if tiles_info is not None:
            entry["tiles"] = tiles_info
        manifest["gen"] = gen
        manifest["ranges"] = ([e for e in manifest["ranges"]
                               if e["out"] != out_seq] + [entry])
        manifest["ranges"].sort(key=lambda e: e["out"])
        manifest["reclaimed_bytes"] = (manifest.get("reclaimed_bytes", 0)
                                       + max(0, orig_bytes - new_bytes))
        self._flip_manifest(manifest)
        self._crash_point("compact_after_flip")
        if not os.path.exists(segs[out_seq]):
            # a concurrent checkpoint truncated the range mid-pass:
            # swapping now would resurrect a pre-anchor segment. The
            # replay-side cost would only be dedup work, but don't.
            os.remove(tmp)
            if os.path.exists(prog_path):
                os.remove(prog_path)
            return None
        os.replace(tmp, segs[out_seq])
        _fsync_dir(self.wal_dir)
        if os.path.exists(prog_path):
            os.remove(prog_path)
        self._crash_point("compact_before_unlink")
        for seq in rng[1:]:
            try:
                os.remove(segs[seq])
            except FileNotFoundError:
                pass
        _fsync_dir(self.wal_dir)
        self._crash_point("compact_after_unlink")
        event = {
            "kind": "wal_compact",
            "out": out_seq,
            "covers": [rng[0], rng[-1]],
            "segments": len(rng),
            "records_in": records_in,
            "records_out": records_out,
            "orig_bytes": orig_bytes,
            "bytes": new_bytes,
            "reclaimed_bytes": max(0, orig_bytes - new_bytes),
            "gen": gen,
        }
        if tiles_info is not None:
            event["tiles"] = {"n": tiles_info["n"],
                              "peak_tile_bytes":
                                  tiles_info["peak_tile_bytes"]}
        with self._lock:
            self.folds += 1
            self.segments_folded += len(rng)
            self.records_in += records_in
            self.records_out += records_out
            self.reclaimed_bytes += event["reclaimed_bytes"]
            self._events.append(event)
        return event

    @staticmethod
    def _write_segment(path: str, records: List[Dict]) -> int:
        import pickle

        with open(path, "wb") as f:
            f.write(_MAGIC)
            n = len(_MAGIC)
            for rec in records:
                body = pickle.dumps(rec)
                f.write(_HEADER.pack(len(body), zlib.crc32(body)))
                f.write(body)
                n += _HEADER.size + len(body)
            f.flush()
            os.fsync(f.fileno())
        return n

    @staticmethod
    def _append_records(path: str, records: List[Dict],
                        at: Optional[int]) -> int:
        """Append pickled frames to a tmp segment at byte offset
        ``at``, truncating anything beyond it first (a torn tile
        append from a crashed attempt). ``at=None`` (re)creates the
        file with the WAL magic. Returns the new end offset."""
        import pickle

        if at is None:
            with open(path, "wb") as f:
                f.write(_MAGIC)
                f.flush()
                os.fsync(f.fileno())
            at = len(_MAGIC)
        with open(path, "r+b") as f:
            f.truncate(at)
            f.seek(at)
            n = at
            for rec in records:
                body = pickle.dumps(rec)
                f.write(_HEADER.pack(len(body), zlib.crc32(body)))
                f.write(body)
                n += _HEADER.size + len(body)
            f.flush()
            os.fsync(f.fileno())
        return n

    @staticmethod
    def _read_progress(path: str) -> Optional[Dict]:
        """The tile-progress sidecar as a dict, or None when absent or
        unusable (a torn/alien sidecar just means a fresh fold)."""
        try:
            with open(path) as f:
                prog = json.load(f)
        except (OSError, ValueError):
            return None
        if prog.get("schema") != PROGRESS_SCHEMA:
            return None
        return prog

    def _write_progress(self, path: str, prog: Dict) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(prog, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.wal_dir)

    def _flip_manifest(self, manifest: Dict) -> None:
        path = os.path.join(self.wal_dir, COMPACT_MANIFEST_FILE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.wal_dir)

    # -- interrupted-pass recovery -----------------------------------------

    def _recover_interrupted(self) -> None:
        """Roll an interrupted pass forward (flip happened), back (it
        didn't), or *hold* it (a tiled pass with a valid progress
        sidecar resumes in the next fold), and prune manifest entries
        for segments a later checkpoint truncated away."""
        manifest = read_compact_manifest(self.wal_dir)
        entries = {e["out"]: e for e in
                   (manifest or {}).get("ranges", [])}
        changed = False
        for fname in sorted(os.listdir(self.wal_dir)):
            if fname.endswith(_PROGRESS_SUFFIX + ".tmp"):
                # torn sidecar flip: the flipped sidecar (if any) is
                # authoritative, the half-written one is garbage
                os.remove(os.path.join(self.wal_dir, fname))
                continue
            if fname.endswith(_PROGRESS_SUFFIX):
                # orphan sidecar (pass completed, crash before the
                # sidecar unlink): harmless, drop it
                base = fname[:-len(_PROGRESS_SUFFIX)]
                if not os.path.exists(os.path.join(
                        self.wal_dir, base + _TMP_SUFFIX)):
                    os.remove(os.path.join(self.wal_dir, fname))
                continue
            if not fname.endswith(_TMP_SUFFIX):
                continue
            tmp = os.path.join(self.wal_dir, fname)
            seg_name = fname[:-len(_TMP_SUFFIX)]
            try:
                seq = int(seg_name[len("wal-"):-len(".log")])
            except ValueError:
                os.remove(tmp)
                continue
            ent = entries.get(seq)
            if (ent is not None
                    and ent["bytes"] == os.path.getsize(tmp)
                    and self._tmp_valid(tmp, seq)):
                # crashed between flip and swap: roll forward
                os.replace(tmp, os.path.join(self.wal_dir, seg_name))
                prog_path = tmp[:-len(_TMP_SUFFIX)] + _PROGRESS_SUFFIX
                if os.path.exists(prog_path):
                    os.remove(prog_path)
                _fsync_dir(self.wal_dir)
            elif (self.tile_bytes and self.tile_bytes > 0
                  and self._read_progress(
                      tmp[:-len(_TMP_SUFFIX)] + _PROGRESS_SUFFIX)
                  is not None):
                # a tiled pass died mid-fold before its flip: the
                # originals are still authoritative (nothing swapped),
                # and the sidecar lets the next fold resume finished
                # tiles instead of refolding — hold the tmp
                continue
            else:
                # crashed before the flip (or the tmp is torn): the
                # originals are authoritative — roll back
                os.remove(tmp)
                prog_path = tmp[:-len(_TMP_SUFFIX)] + _PROGRESS_SUFFIX
                if os.path.exists(prog_path):
                    os.remove(prog_path)
                if ent is not None:
                    del entries[seq]
                    changed = True
        # resume unlinks: originals inside a flipped range are
        # superseded (their ids all live on the folded segment)
        live = dict(list_segments(self.wal_dir))
        for seq, ent in list(entries.items()):
            if seq not in live:
                del entries[seq]  # truncated by a checkpoint
                changed = True
                continue
            for s in range(ent["covers"][0] + 1, ent["covers"][1] + 1):
                if s in live:
                    try:
                        os.remove(live[s])
                    except FileNotFoundError:
                        pass
        if manifest is not None and changed:
            manifest["ranges"] = sorted(entries.values(),
                                        key=lambda e: e["out"])
            self._flip_manifest(manifest)

    def _tmp_valid(self, tmp: str, seq: int) -> bool:
        try:
            _read_segment(tmp, seq, False)
            return True
        except WalError:
            return False

    # -- thread + supervision ----------------------------------------------

    @property
    def alive(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> "WalCompactor":
        if self.alive:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="wal-compactor", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.compact_once()
            except Exception as e:  # noqa: BLE001 - surface via supervision
                self.last_error = e
                raise

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)

    def restart(self) -> bool:
        """Supervision hook (ControlPlane): respawn a dead compactor
        thread. Returns False if it is still alive (nothing to do)."""
        if self.alive:
            return False
        self.last_error = None
        self.restarts += 1
        self._thread = None
        self.start()
        return True

    def drain_events(self) -> List[Dict]:
        """Completed-pass events since the last drain (the ControlPlane
        turns these into ``wal_compact`` actions)."""
        with self._lock:
            out, self._events = self._events, []
        return out

    def close(self) -> None:
        self.stop()
        for reg, name in self._metric_names:
            reg.unregister_prefix(name)
        self._metric_names.clear()

    # -- observability -----------------------------------------------------

    def publish_metrics(self, registry=None, name: str = "compact"
                        ) -> None:
        reg = registry if registry is not None else REGISTRY
        reg.gauge(f"{name}.folds", lambda: self.folds)
        reg.gauge(f"{name}.segments_folded",
                  lambda: self.segments_folded)
        reg.gauge(f"{name}.reclaimed_bytes",
                  lambda: self.reclaimed_bytes)
        reg.gauge(f"{name}.reclaimable_bytes", self.reclaimable_bytes)
        reg.gauge(f"{name}.log_bytes", self.log_bytes)
        reg.gauge(f"{name}.restarts", lambda: self.restarts)
        reg.gauge(f"{name}.tile_folds", lambda: self.tile_folds)
        reg.gauge(f"{name}.peak_tile_bytes",
                  lambda: self.peak_tile_bytes)
        self._metric_names.append((reg, name))
