"""Segmented append-only write-ahead log of pushed source batches.

On-disk layout: ``<wal_dir>/wal-<seq>.log`` segment files, each starting
with an 8-byte magic header, followed by length+CRC framed records::

    [u32 payload_len][u32 crc32(payload)][payload]

The payload is a pickled record dict (the same serialization the
checkpoint module uses for host state). Three record kinds flow through
the log:

- ``push``: one accepted source batch — serialized ``DeltaBatch``
  columns + ``batch_id`` + source node id/name + the tick horizon at
  append time;
- ``tick``: a tick-boundary commit marker (appended after the tick
  completes);
- ``ckpt``: informational marker stamped at checkpoint rotation.

Durability contract by fsync policy (``fsync=``):

- ``"record"``: flush + fsync after every append — survives power loss
  per accepted batch; highest latency.
- ``"tick"`` (default): flush per append (page cache — survives process
  death), fsync once per tick boundary — a power loss can lose at most
  the current in-flight tick, never a committed one.
- ``"os"``: flush per append, no per-record/per-tick fsync — survives
  process death only; the OS decides when bytes hit disk (segment
  rotation still fsyncs the sealed file, whatever the policy).

A crashed process may leave a torn final record (partial write). The
read side (:func:`scan_wal`) tolerates exactly that: a bad frame at the
tail of the *last* segment truncates the log there; a bad frame
anywhere else is real corruption and raises :class:`WalError`. A fresh
:class:`WriteAheadLog` never appends to an existing segment (the tail
may be torn) — it always opens a new one.
"""

from __future__ import annotations

import os
import pickle
import re
import struct
import threading
import time
import zlib
from collections import deque
from typing import (Deque, Dict, Iterable, List, NamedTuple, Optional,
                    Tuple)

from reflow_tpu.obs import trace as _trace

__all__ = ["LogPosition", "TornTail", "WalError", "WriteAheadLog",
           "list_segments", "scan_wal"]

_MAGIC = b"RFWAL001"
_HEADER = struct.Struct("<II")  # payload_len, crc32
_SEG_RE = re.compile(r"^wal-(\d{8})\.log$")
#: frame-length sanity bound — a "length" beyond this is a torn/corrupt
#: header, not a real record (segments rotate long before this)
_MAX_RECORD = 1 << 30
#: latency/group-size sample retention (percentile inputs only — the
#: ``appends``/``fsyncs``/``bytes_written`` counters stay exact)
_METRIC_WINDOW = 4096


class WalError(RuntimeError):
    """Corruption in a sealed (non-tail) region of the log."""


class LogPosition(NamedTuple):
    """Byte position in the log: (segment sequence number, offset)."""

    segment: int
    offset: int


class TornTail(NamedTuple):
    """Where and why the tail of the last segment stopped parsing."""

    segment: int
    offset: int
    reason: str


def _seg_path(wal_dir: str, seq: int) -> str:
    return os.path.join(wal_dir, f"wal-{seq:08d}.log")


def list_segments(wal_dir: str) -> List[Tuple[int, str]]:
    """Sorted [(seq, path)] of the segment files present in ``wal_dir``."""
    if not os.path.isdir(wal_dir):
        return []
    out = []
    for name in os.listdir(wal_dir):
        m = _SEG_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(wal_dir, name)))
    return sorted(out)


class WriteAheadLog:
    """Appender over a directory of rotating segment files.

    Latency accounting (``utils.metrics.summarize_wal``): every append
    and fsync wall is recorded in ``append_s`` / ``fsync_s``, and
    ``appends`` / ``fsyncs`` / ``bytes_written`` count totals.

    Thread safety + group commit (ROADMAP open item): appends are safe
    from concurrent threads, and under ``fsync="record"`` the fsync is a
    classic *group commit* — a writer whose frame was already covered by
    another writer's fsync (or by :meth:`append_group`'s single barrier
    over a whole coalescing window) skips its own. ``group_sizes``
    records how many appends each fsync covered; >1 means grouping
    engaged (the serving frontend's coalescing window is the hot
    producer of large groups).
    """

    POLICIES = ("record", "tick", "os")

    def __init__(self, wal_dir: str, *, fsync: str = "tick",
                 segment_bytes: int = 16 << 20):
        if fsync not in self.POLICIES:
            raise ValueError(f"fsync policy {fsync!r} not in {self.POLICIES}")
        self.wal_dir = wal_dir
        self.fsync_policy = fsync
        self.segment_bytes = segment_bytes
        os.makedirs(wal_dir, exist_ok=True)
        segs = list_segments(wal_dir)
        #: torn tail repaired at open, if any (surfaced by recovery)
        self.repaired_tail: Optional[TornTail] = None
        if segs:
            # self-healing open: truncate a crashed generation's torn
            # final record to the valid prefix BEFORE opening a new
            # segment — otherwise the tear would sit in a sealed
            # (non-final) segment and read as corruption forever after
            self.repaired_tail = _repair_tail(segs[-1][1], segs[-1][0])
        # never resume an existing segment: append offsets are only
        # known-good for a segment this process wrote start to finish
        self._seq = (segs[-1][0] + 1) if segs else 0
        self._f = None
        self.appends = 0
        self.fsyncs = 0
        self.bytes_written = 0
        # bounded reservoirs (most recent _METRIC_WINDOW samples): the
        # counters above are exact; only percentile inputs are windowed,
        # so a long-running server's log can't leak through its metrics
        self.append_s: Deque[float] = deque(maxlen=_METRIC_WINDOW)
        self.fsync_s: Deque[float] = deque(maxlen=_METRIC_WINDOW)
        #: appends covered per fsync (group-commit effectiveness)
        self.group_sizes: Deque[int] = deque(maxlen=_METRIC_WINDOW)
        self._lock = threading.RLock()
        self._unsynced_appends = 0
        #: (segment, offset) durably synced through — the group-commit
        #: free-ride check compares a frame's end position against this
        self._synced_pos = (self._seq, 0)
        self._open_segment()

    # -- write side --------------------------------------------------------

    def _open_segment(self) -> None:
        self._f = open(_seg_path(self.wal_dir, self._seq), "wb")
        self._f.write(_MAGIC)
        self._f.flush()
        self._offset = len(_MAGIC)

    def _write_frame(self, record: Dict) -> Tuple[LogPosition,
                                                  Tuple[int, int]]:
        # caller holds self._lock; returns (position, end-of-frame mark)
        t0 = time.perf_counter()
        payload = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
        frame = _HEADER.pack(len(payload), zlib.crc32(payload)) + payload
        pos = LogPosition(self._seq, self._offset)
        self._f.write(frame)
        # page cache is the floor for every policy: a killed process
        # must never take back a record the scheduler already accepted
        self._f.flush()
        self._offset += len(frame)
        self.appends += 1
        self._unsynced_appends += 1
        self.bytes_written += len(frame)
        self.append_s.append(time.perf_counter() - t0)
        if _trace.ENABLED:
            dur = time.perf_counter() - t0
            _trace.evt("wal_append", t0, dur, track="wal",
                       args={"bytes": len(frame)})
            _trace.wal_accum_add(dur)
        end = (self._seq, self._offset)
        if self._offset >= self.segment_bytes:
            self.rotate()
        return pos, end

    def append(self, record: Dict) -> LogPosition:
        """Frame + append one record; returns its position. Honors the
        ``"record"`` fsync policy (with group commit — see the class
        docstring); ``"tick"`` batches the fsync into :meth:`note_tick`.
        """
        with self._lock:
            pos, end = self._write_frame(record)
        if self.fsync_policy == "record":
            self._record_fsync(end)
        return pos

    def append_group(self, records: Iterable[Dict]) -> List[LogPosition]:
        """Append several records under ONE durability barrier: the
        explicit group-commit path for a coalescing window whose batches
        commit atomically anyway (``DurableScheduler.tick_many``). Under
        ``"record"`` the group shares a single fsync."""
        with self._lock:
            out = [self._write_frame(r) for r in records]
        if out and self.fsync_policy == "record":
            self._record_fsync(out[-1][1])
        return [pos for pos, _end in out]

    def _record_fsync(self, end: Tuple[int, int]) -> None:
        # group commit: the first writer to reach the lock fsyncs for
        # every frame written so far; a writer whose frame is already
        # covered (rotation sealed it, or another writer's fsync passed
        # it) takes the free ride
        with self._lock:
            if self._synced_pos >= end:
                return
            self._fsync()

    def _fsync(self) -> None:
        # caller holds self._lock
        t0 = time.perf_counter()
        os.fsync(self._f.fileno())
        self.fsyncs += 1
        self.fsync_s.append(time.perf_counter() - t0)
        if _trace.ENABLED:
            dur = time.perf_counter() - t0
            _trace.evt("wal_fsync", t0, dur, track="wal",
                       args={"covered": self._unsynced_appends})
            _trace.wal_accum_add(dur)
        if self._unsynced_appends:
            self.group_sizes.append(self._unsynced_appends)
            self._unsynced_appends = 0
        self._synced_pos = max(self._synced_pos, (self._seq, self._offset))

    def note_tick(self) -> None:
        """Tick-boundary durability barrier (``"tick"`` policy fsyncs
        here; ``"record"`` already did; ``"os"`` never does)."""
        if self.fsync_policy == "tick":
            with self._lock:
                self._fsync()

    def sync(self) -> None:
        """Unconditional durability barrier (checkpoint path)."""
        with self._lock:
            self._f.flush()
            self._fsync()

    def position(self) -> LogPosition:
        """Position one past the last appended byte."""
        with self._lock:
            return LogPosition(self._seq, self._offset)

    def rotate(self) -> None:
        """Seal the current segment and open the next one. The sealed
        segment is fsynced before close — whatever the policy, bytes in
        a sealed segment are durable (so the group-commit free-ride
        check can trust ``_synced_pos`` across rotations, and a
        mid-tick rotation can't strand committed records in the page
        cache)."""
        with self._lock:
            self._f.flush()
            self._fsync()
            self._f.close()
            self._seq += 1
            self._open_segment()

    def truncate_until(self, pos: LogPosition) -> List[str]:
        """Delete sealed segments strictly before ``pos.segment`` (the
        checkpoint already covers them). Returns the removed paths."""
        removed = []
        for seq, path in list_segments(self.wal_dir):
            if seq < pos.segment and seq != self._seq:
                os.remove(path)
                removed.append(path)
        return removed

    def publish_metrics(self, registry=None, *, name: str = "wal"
                        ) -> str:
        """Register this log's live summary (the ``summarize_wal``
        schema: append/fsync latency percentiles, group-commit shape)
        as an obs metric source. Returns the source key."""
        from reflow_tpu.obs import REGISTRY
        from reflow_tpu.utils.metrics import summarize_wal
        reg = registry if registry is not None else REGISTRY
        reg.register_source(name,
                            lambda: summarize_wal(self).to_dict())
        reg.gauge(f"{name}.fsync_rate",
                  lambda: self.fsyncs / max(self.appends, 1))
        return name

    def close(self) -> None:
        with self._lock:
            if self._f is not None and not self._f.closed:
                self._f.flush()
                self._fsync()
                self._f.close()


# -- read side -------------------------------------------------------------

def _valid_prefix(data: bytes) -> int:
    """Byte length of the longest valid record prefix (past the magic);
    -1 when even the magic is gone."""
    if data[:len(_MAGIC)] != _MAGIC:
        return -1
    off = len(_MAGIC)
    while off + _HEADER.size <= len(data):
        length, crc = _HEADER.unpack_from(data, off)
        payload = data[off + _HEADER.size: off + _HEADER.size + length]
        if (length > _MAX_RECORD or len(payload) < length
                or zlib.crc32(payload) != crc):
            break
        off += _HEADER.size + length
    return off


def _repair_tail(path: str, seq: int) -> Optional[TornTail]:
    """Truncate ``path`` to its valid record prefix (drop a torn final
    record); delete it outright if even the magic header is torn.
    Returns what was repaired, or None for an already-clean segment."""
    with open(path, "rb") as f:
        data = f.read()
    keep = _valid_prefix(data)
    if keep == len(data):
        return None
    if keep < 0:
        os.remove(path)
        return TornTail(seq, 0, "segment magic torn; segment removed")
    with open(path, "rb+") as f:
        f.truncate(keep)
    return TornTail(seq, keep,
                    f"torn record truncated ({len(data) - keep} bytes)")

def _read_segment(path: str, seq: int, is_last: bool,
                  ) -> Tuple[List[Tuple[LogPosition, Dict]],
                             Optional[TornTail]]:
    records: List[Tuple[LogPosition, Dict]] = []

    def bad(offset: int, reason: str):
        if is_last:
            return records, TornTail(seq, offset, reason)
        raise WalError(f"{path} @ {offset}: {reason} in a sealed "
                       f"(non-final) segment — real corruption, not a "
                       f"torn tail")

    with open(path, "rb") as f:
        data = f.read()
    if data[:len(_MAGIC)] != _MAGIC:
        return bad(0, f"bad segment magic {data[:len(_MAGIC)]!r}")
    off = len(_MAGIC)
    while off < len(data):
        if off + _HEADER.size > len(data):
            return bad(off, "truncated frame header")
        length, crc = _HEADER.unpack_from(data, off)
        if length > _MAX_RECORD:
            return bad(off, f"implausible frame length {length}")
        payload = data[off + _HEADER.size: off + _HEADER.size + length]
        if len(payload) < length:
            return bad(off, f"truncated payload ({len(payload)}/{length} "
                            f"bytes)")
        if zlib.crc32(payload) != crc:
            return bad(off, "CRC mismatch")
        try:
            record = pickle.loads(payload)
        except Exception as e:  # noqa: BLE001 - framed+CRC-clean yet unloadable
            return bad(off, f"unpicklable payload ({e})")
        records.append((LogPosition(seq, off), record))
        off += _HEADER.size + length
    return records, None


def scan_wal(wal_dir: str, start: Optional[Tuple[int, int]] = None,
             ) -> Tuple[List[Tuple[LogPosition, Dict]], Optional[TornTail]]:
    """Parse every record at or after ``start`` ((segment, offset), e.g.
    a checkpoint's recorded position). Returns ``(records, torn)`` where
    ``torn`` describes a tolerated torn tail in the final segment (None
    for a clean log). Raises :class:`WalError` on non-tail corruption.
    """
    segs = list_segments(wal_dir)
    records: List[Tuple[LogPosition, Dict]] = []
    torn: Optional[TornTail] = None
    for ix, (seq, path) in enumerate(segs):
        if start is not None and seq < start[0]:
            continue
        seg_records, torn = _read_segment(path, seq, ix == len(segs) - 1)
        for pos, rec in seg_records:
            if start is not None and pos.segment == start[0] \
                    and pos.offset < start[1]:
                continue
            records.append((pos, rec))
    return records, torn


def iter_push_records(records: Iterable[Tuple[LogPosition, Dict]]):
    """The push records of a scan, in log order."""
    for pos, rec in records:
        if rec.get("kind") == "push":
            yield pos, rec
