"""Segmented append-only write-ahead log of pushed source batches.

On-disk layout: ``<wal_dir>/wal-<seq>.log`` segment files, each starting
with an 8-byte magic header, followed by length+CRC framed records::

    [u32 payload_len][u32 crc32(payload)][payload]

The payload is a pickled record dict (the same serialization the
checkpoint module uses for host state). Three record kinds flow through
the log:

- ``push``: one accepted source batch — serialized ``DeltaBatch``
  columns + ``batch_id`` + source node id/name + the tick horizon at
  append time;
- ``tick``: a tick-boundary commit marker (appended after the tick
  completes);
- ``ckpt``: informational marker stamped at checkpoint rotation.

Durability contract by fsync policy (``fsync=``):

- ``"record"``: every append is fsynced before it is *acknowledged* —
  survives power loss per accepted batch; highest latency.
- ``"tick"`` (default): flush per append (page cache — survives process
  death), fsync once per tick boundary — a power loss can lose at most
  the current in-flight tick, never a committed one.
- ``"os"``: flush per append, no per-record/per-tick fsync — survives
  process death only; the OS decides when bytes hit disk (segment
  rotation still fsyncs the sealed file, whatever the policy).

Pipelined commit (the asynchronous committer)
---------------------------------------------

With ``committer="thread"`` (the default) the dispatch path never
touches the disk: ``append``/``append_group`` pickle the record, assign
it a monotonically increasing **LSN** and an exact ``LogPosition``
(offset bookkeeping is synchronous), enqueue the framed bytes on an
in-memory commit queue, and return. A dedicated *committer* thread
(``reflow-wal-committer``) drains the queue in LSN order and performs
the ``write`` + ``flush`` + ``os.fsync`` syscalls, advancing two
watermarks: *flushed* (written to the page cache — process-death
durable) and *synced* (fsynced — power-loss durable). Callers gate
acknowledgement on :meth:`wait_durable` / :meth:`when_durable`, so
window N's framing, write and fsync all overlap window N+1's host merge
and device dispatch. What ``wait_durable(lsn)`` guarantees per policy:

========  =========================================================
policy    ``wait_durable(lsn)`` returns once the frame is …
========  =========================================================
record    fsynced (power-loss durable)
tick      fsynced at the covering tick barrier (power-loss durable)
os        written + flushed (process-death durable; no fsync wait)
========  =========================================================

A record an appender has enqueued but the committer has not yet written
is NOT yet process-death durable — which is exactly why every
acknowledgement path gates on the watermarks above, and why a crash
that loses queued frames loses only *unacknowledged* batches (the
upstream re-sends; replay dedups). ``committer="inline"`` restores the
fully synchronous pre-pipeline behavior — every frame is written and
every barrier fsynced in the appending thread (the
``REFLOW_BENCH_WALPIPE=1`` baseline).

A crashed process may leave a torn final record (partial write). The
read side (:func:`scan_wal`) tolerates exactly that: a bad frame at the
tail of the *last* segment truncates the log there; a bad frame
anywhere else is real corruption and raises :class:`WalError`. A fresh
:class:`WriteAheadLog` never appends to an existing segment (the tail
may be torn) — it always opens a new one.
"""

from __future__ import annotations

import os
import pickle
import re
import struct
import threading
import time
import zlib
from collections import deque
from typing import (Callable, Deque, Dict, Iterable, List, NamedTuple,
                    Optional, Tuple)

from reflow_tpu.obs import trace as _trace
from reflow_tpu.utils.runtime import named_lock

__all__ = ["FencedWrite", "LogPosition", "TornTail", "WalError",
           "WriteAheadLog", "list_segments", "scan_wal"]

_MAGIC = b"RFWAL001"
_HEADER = struct.Struct("<II")  # payload_len, crc32
_SEG_RE = re.compile(r"^wal-(\d{8})\.log$")
#: frame-length sanity bound — a "length" beyond this is a torn/corrupt
#: header, not a real record (segments rotate long before this)
_MAX_RECORD = 1 << 30
#: latency/group-size sample retention (percentile inputs only — the
#: ``appends``/``fsyncs``/``bytes_written`` counters stay exact)
_METRIC_WINDOW = 4096


class WalError(RuntimeError):
    """Corruption in a sealed (non-tail) region of the log."""


class FencedWrite(WalError):
    """A write was refused because this log's epoch has been fenced: a
    newer leader epoch was minted at promotion (``wal/ship.py`` /
    ``serve/failover.py``), so this writer is a zombie ex-leader. Its
    appends must never reach the replicated history — they are rejected
    here, and the epoch stamped into every record lets receivers reject
    anything that slipped onto disk before the fence landed."""


#: on-disk sidecar recording the log's epoch + fence state so offline
#: tooling (tools/wal_inspect.py) can report it after the process died
FENCE_STATE_SCHEMA = "reflow.wal_fence/1"
_FENCE_STATE_FILE = "fence-state.json"


class LogPosition(NamedTuple):
    """Byte position in the log: (segment sequence number, offset)."""

    segment: int
    offset: int


class TornTail(NamedTuple):
    """Where and why the tail of the last segment stopped parsing."""

    segment: int
    offset: int
    reason: str


def _seg_path(wal_dir: str, seq: int) -> str:
    return os.path.join(wal_dir, f"wal-{seq:08d}.log")


def list_segments(wal_dir: str) -> List[Tuple[int, str]]:
    """Sorted [(seq, path)] of the segment files present in ``wal_dir``."""
    if not os.path.isdir(wal_dir):
        return []
    out = []
    for name in os.listdir(wal_dir):
        m = _SEG_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(wal_dir, name)))
    return sorted(out)


class WriteAheadLog:
    """Appender over a directory of rotating segment files.

    Latency accounting (``utils.metrics.summarize_wal``): every append
    and fsync wall is recorded in ``append_s`` / ``fsync_s``, and
    ``appends`` / ``fsyncs`` / ``bytes_written`` count totals. With the
    threaded committer ``append_s`` measures the *dispatch-path* cost
    (pickle + enqueue); the write/fsync syscall wall lands in
    ``fsync_s`` on the committer.

    Thread safety + group commit (ROADMAP open item): appends are safe
    from concurrent threads, and under ``fsync="record"`` the fsync is a
    classic *group commit* — the committer drains every pending frame
    and durability request with ONE fsync, and a request already
    covered by the durable watermark (rotation sealed it, or an earlier
    fsync passed it) rides for free. ``group_sizes`` records how many
    appends each fsync covered; >1 means grouping engaged (the serving
    frontend's coalescing window is the hot producer of large groups).

    Locking: ``self._lock`` (an RLock) guards all appender state — LSN
    and offset bookkeeping, the commit queue, the watermarks. The
    committer performs its syscalls with ``_lock`` RELEASED (holding
    only ``_sync_lock``, which orders fsync/close against fd swaps), so
    appends keep flowing during the disk wait; lock order is
    ``_lock`` → ``_sync_lock``. Durable callbacks registered via
    :meth:`when_durable` fire *under* ``_lock`` (in LSN order, on
    whichever thread advanced the watermark) — callbacks may take their
    own locks but must never call back into a lock that is held while
    calling WAL methods (the serve frontend never holds its admission
    lock across a WAL call, so WAL-lock → frontend-lock is a safe
    order).
    """

    POLICIES = ("record", "tick", "os")
    COMMITTERS = ("thread", "inline")

    def __init__(self, wal_dir: str, *, fsync: str = "tick",
                 segment_bytes: int = 16 << 20,
                 committer: str = "thread", crash=None, epoch: int = 0):
        if fsync not in self.POLICIES:
            raise ValueError(f"fsync policy {fsync!r} not in {self.POLICIES}")
        if committer not in self.COMMITTERS:
            raise ValueError(
                f"committer {committer!r} not in {self.COMMITTERS}")
        self.wal_dir = wal_dir
        self.fsync_policy = fsync
        self.segment_bytes = segment_bytes
        self._crash = crash
        #: leader-epoch token stamped into every appended record (and
        #: into the shipper's Shipments): minted at promotion, so a
        #: receiver can tell a live leader's bytes from a zombie's
        self._epoch = int(epoch)
        #: the newer epoch that fenced this log (None = not fenced)
        self._fenced_by: Optional[int] = None
        #: appends refused because the log was fenced (zombie writer)
        self.fence_rejected_appends = 0
        os.makedirs(wal_dir, exist_ok=True)
        # a fenced log STAYS fenced across restarts: a zombie that
        # crashes and reopens its old directory must not come back
        # writable (the sidecar is best-effort, but so is the zombie's
        # luck — replicas reject its shipments by epoch regardless)
        try:
            import json
            with open(os.path.join(wal_dir, _FENCE_STATE_FILE)) as f:
                saved = json.load(f)
            self._epoch = max(self._epoch, int(saved.get("epoch") or 0))
            fb = saved.get("fenced_by")
            if fb is not None and int(fb) > self._epoch:
                self._fenced_by = int(fb)
        except (OSError, ValueError):
            pass
        segs = list_segments(wal_dir)
        #: torn tail repaired at open, if any (surfaced by recovery)
        self.repaired_tail: Optional[TornTail] = None
        if segs:
            # self-healing open: truncate a crashed generation's torn
            # final record to the valid prefix BEFORE opening a new
            # segment — otherwise the tear would sit in a sealed
            # (non-final) segment and read as corruption forever after
            self.repaired_tail = _repair_tail(segs[-1][1], segs[-1][0])
        # never resume an existing segment: append offsets are only
        # known-good for a segment this process wrote start to finish
        self._seq = (segs[-1][0] + 1) if segs else 0
        self._f = None
        self.appends = 0
        self.fsyncs = 0
        self.bytes_written = 0
        # bounded reservoirs (most recent _METRIC_WINDOW samples): the
        # counters above are exact; only percentile inputs are windowed,
        # so a long-running server's log can't leak through its metrics
        self.append_s: Deque[float] = deque(maxlen=_METRIC_WINDOW)
        self.fsync_s: Deque[float] = deque(maxlen=_METRIC_WINDOW)
        #: appends covered per fsync (group-commit effectiveness)
        self.group_sizes: Deque[int] = deque(maxlen=_METRIC_WINDOW)
        self._lock = named_lock("wal.log", reentrant=True)
        #: orders the fsync/close syscalls against fd swaps (rotation,
        #: close): any path that closes the fd takes it, so a file is
        #: never closed mid-fsync. Lock order: ``_lock`` →
        #: ``_sync_lock`` (the committer never takes ``_lock`` while
        #: holding ``_sync_lock``)
        self._sync_lock = named_lock("wal.sync")
        self._unsynced_appends = 0
        #: LSN watermarks, all process-local and monotonic:
        #: ``_written_lsn`` — last LSN *assigned* (frame pickled +
        #: enqueued; with the inline committer also written);
        #: ``_flushed_lsn`` — written + flushed to the page cache
        #: (process-death durable, the ``"os"`` gate);
        #: ``_synced_lsn`` — fsynced (power-loss durable, the
        #: ``"record"``/``"tick"`` gate and group-commit free-ride
        #: check)
        self._written_lsn = 0
        self._flushed_lsn = 0
        self._synced_lsn = 0
        #: byte-position twin of ``_synced_lsn``: everything strictly
        #: before this (segment, offset) is on disk AND fsynced — the
        #: prefix a WAL shipper (wal/ship.py) may stream to followers.
        #: Maintained from ``_lsn_pos`` (frame LSN -> frame end
        #: position), popped as the synced watermark advances.
        self._synced_pos = LogPosition(self._seq, len(_MAGIC))
        self._lsn_pos: Deque[Tuple[int, int, int]] = deque()
        #: committer work queue, strictly FIFO == LSN order:
        #: ("frame", bytes, lsn) | ("rotate", new_seq, cover_lsn) |
        #: ("fsync", target_lsn, t_enqueued)
        self._io_q: Deque[tuple] = deque()
        #: gauge mirror of pending durability requests (lsn, t) — feeds
        #: queue_depth()/durable_lag_s(); popped as the watermark passes
        self._fsync_q: Deque[Tuple[int, float]] = deque()
        #: (lsn, fn) continuations fired once lsn is durable (LSN order)
        self._callbacks: Deque[Tuple[int,
                                     Callable[[Optional[BaseException]],
                                              None]]] = deque()
        self._commit_cv = threading.Condition(self._lock)   # committer
        self._durable_cv = threading.Condition(self._lock)  # waiters
        self._closing = False
        self._metric_keys: list = []  # (registry, key) published
        #: True while the committer is mid-batch (drain() barrier)
        self._io_busy = False
        self.committer_error: Optional[BaseException] = None
        #: supervision counters: how many times a dead committer was
        #: respawned (:meth:`restart_committer`), and the cause of the
        #: most recent death (kept after the error is cleared so the
        #: control plane can report WHY it restarted)
        self.committer_restarts = 0
        self.last_committer_error: Optional[BaseException] = None
        self._open_segment()
        if self._epoch:
            self._persist_fence_locked()
        #: highest segment seq the committer has finished opening
        #: (thread-mode rotate() barrier)
        self._rotated_seq = self._seq
        self._committer: Optional[threading.Thread] = None
        if committer == "thread":
            self._committer = threading.Thread(
                target=self._committer_loop, name="reflow-wal-committer",
                daemon=True)
            self._committer.start()

    # -- crash seams (tests only) ------------------------------------------

    def _crash_point(self, name: str) -> None:
        if self._crash is not None:
            self._crash.point(name)

    # -- write side --------------------------------------------------------

    def _open_segment(self) -> None:
        self._f = open(_seg_path(self.wal_dir, self._seq), "wb")
        self._f.write(_MAGIC)
        self._f.flush()
        self._offset = len(_MAGIC)

    def _frame(self, record: Dict) -> bytes:
        # records from a promoted leader carry its epoch: receivers
        # (replicas, recovery) can reject/attribute bytes by leader
        # generation even when they arrived on disk before a fence
        # landed. The binary frame layout is unchanged — the token
        # rides in the pickled dict — and epoch 0 (the founding
        # leader) stays UNstamped, so its bytes are identical to a
        # pre-failover log's (an absent key reads as epoch 0
        # everywhere).
        if self._epoch and record.get("epoch") != self._epoch:
            record = {**record, "epoch": self._epoch}
        payload = pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL)
        return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload

    def _append_frame(self, record: Dict) -> Tuple[LogPosition, int]:
        # caller holds self._lock; returns (position, frame LSN)
        if self._committer is not None:
            return self._enqueue_frame(record)
        return self._write_frame(record)

    def _enqueue_frame(self, record: Dict) -> Tuple[LogPosition, int]:
        # threaded committer: the dispatch path only pickles and does
        # position/LSN bookkeeping — write+flush+fsync happen on the
        # committer, strictly in enqueue (== LSN) order
        t0 = time.perf_counter()
        frame = self._frame(record)
        pos = LogPosition(self._seq, self._offset)
        self._offset += len(frame)
        self.appends += 1
        self._unsynced_appends += 1
        self.bytes_written += len(frame)
        self._written_lsn += 1
        lsn = self._written_lsn
        self._lsn_pos.append((lsn, pos.segment, pos.offset + len(frame)))
        self._io_q.append(("frame", frame, lsn))
        if self._offset >= self.segment_bytes:
            # bookkeeping rotation: later frames get positions in the
            # next segment; the committer performs the actual
            # seal-fsync/close/open when it reaches this command
            self._seq += 1
            self._io_q.append(("rotate", self._seq, lsn))
            self._offset = len(_MAGIC)
        self._commit_cv.notify()
        # the seam fires only once the enqueue is complete (committer
        # woken): a crash "after enqueue" must not strand the frame in a
        # queue nobody is draining
        self._crash_point("wal_enqueue")
        self.append_s.append(time.perf_counter() - t0)
        if _trace.ENABLED:
            _trace.evt("wal_append", t0, time.perf_counter() - t0,
                       track="wal", args={"bytes": len(frame), "lsn": lsn})
        return pos, lsn

    def _write_frame(self, record: Dict) -> Tuple[LogPosition, int]:
        # inline committer: frame + write + flush synchronously (the
        # pre-pipeline behavior); caller holds self._lock
        self._crash_point("wal_before_write")
        t0 = time.perf_counter()
        frame = self._frame(record)
        pos = LogPosition(self._seq, self._offset)
        self._f.write(frame)
        # page cache is the floor for every policy: a killed process
        # must never take back a record the scheduler already accepted
        self._f.flush()
        self._offset += len(frame)
        self.appends += 1
        self._unsynced_appends += 1
        self.bytes_written += len(frame)
        self._written_lsn += 1
        self._flushed_lsn = self._written_lsn
        lsn = self._written_lsn
        self._lsn_pos.append((lsn, pos.segment, pos.offset + len(frame)))
        self.append_s.append(time.perf_counter() - t0)
        if _trace.ENABLED:
            _trace.evt("wal_append", t0, time.perf_counter() - t0,
                       track="wal", args={"bytes": len(frame), "lsn": lsn})
        self._crash_point("wal_after_write")
        if self._offset >= self.segment_bytes:
            self.rotate()
        return pos, lsn

    def append(self, record: Dict, *, wait: bool = True) -> LogPosition:
        """Frame + append one record; returns its (exact) position.
        Under ``"record"`` a durability request is enqueued for the
        frame and (``wait=True``, the default) acknowledged only once
        durable; ``wait=False`` returns immediately after the enqueue —
        the caller gates on :meth:`wait_durable`/:meth:`when_durable`
        with :meth:`last_lsn`. ``"tick"`` batches the fsync into
        :meth:`note_tick`."""
        with self._lock:
            self._raise_if_fenced()
            self._raise_if_committer_dead()
            pos, lsn = self._append_frame(record)
            if self.fsync_policy == "record":
                self._request_durable(lsn)
        if wait and self.fsync_policy == "record":
            self.wait_durable(lsn)
        return pos

    def append_group(self, records: Iterable[Dict], *, wait: bool = True,
                     request: bool = True) -> List[LogPosition]:
        """Append several records under ONE durability barrier: the
        explicit group-commit path for a coalescing window whose batches
        commit atomically anyway (``DurableScheduler.tick_many``). Under
        ``"record"`` the group shares a single fsync. An empty group is
        a complete no-op — no write, no fsync, no positions.

        ``request=False`` skips even the durability *request*: the
        caller is about to append a later group in the same logical
        commit (data before markers) and wants one barrier for the
        whole window, not one per group. The caller owns the follow-up
        — it must issue a request (or an explicit ``wait_durable``)
        covering these frames before acknowledging anything."""
        records = list(records)
        if not records:
            return []
        with self._lock:
            self._raise_if_fenced()
            self._raise_if_committer_dead()
            out = [self._append_frame(r) for r in records]
            lsn = out[-1][1]
            if request and self.fsync_policy == "record":
                self._request_durable(lsn)
        if wait and request and self.fsync_policy == "record":
            self.wait_durable(lsn)
        return [pos for pos, _lsn in out]

    # -- durability pipeline ----------------------------------------------

    def last_lsn(self) -> int:
        """LSN of the most recently appended frame (0 = nothing yet).
        Monotonic within this process — replay does not persist it."""
        with self._lock:
            return self._written_lsn

    def _durable_point(self) -> int:
        # caller holds self._lock: the watermark the current policy's
        # durability promise gates on
        if self.fsync_policy == "os":
            return self._flushed_lsn
        return self._synced_lsn

    def durable_lsn(self) -> int:
        """Highest LSN the policy's durability promise already covers."""
        with self._lock:
            return self._durable_point()

    def queue_depth(self) -> int:
        """Committer backlog: frames + barriers awaiting the committer
        thread (0 with the inline committer — nothing is deferred)."""
        with self._lock:
            return len(self._io_q)

    def durable_lag_s(self) -> float:
        """Age of the oldest pending durability request (0.0 when the
        committer is caught up)."""
        with self._lock:
            if not self._fsync_q:
                return 0.0
            return time.perf_counter() - self._fsync_q[0][1]

    def _raise_if_committer_dead(self) -> None:
        # caller holds self._lock — fail fast instead of accepting
        # appends whose write/fsync no one will ever serve
        if self.committer_error is not None:
            raise self.committer_error

    # -- epoch fencing -----------------------------------------------------

    @property
    def epoch(self) -> int:
        """Leader epoch stamped into every appended record."""
        with self._lock:
            return self._epoch

    @property
    def fenced(self) -> bool:
        with self._lock:
            return self._fenced_by is not None

    def adopt_epoch(self, epoch: int) -> None:
        """Raise this log's epoch to ``epoch`` (never lowers it) — the
        recovery path: a restarted leader must come back writing in the
        highest epoch its log already contains, or its fresh records
        would read as a zombie's."""
        with self._lock:
            if epoch > self._epoch and (self._fenced_by is None
                                        or epoch >= self._fenced_by):
                self._epoch = int(epoch)
                if self._fenced_by is not None \
                        and self._epoch >= self._fenced_by:
                    self._fenced_by = None  # caught up: fence satisfied
                self._persist_fence_locked()

    def fence(self, new_epoch: int) -> bool:
        """Fence this log out of epochs below ``new_epoch``: a promotion
        minted a newer leader generation, so every subsequent append on
        this (now zombie) writer raises :class:`FencedWrite` instead of
        growing the replicated history. Idempotent; returns True when
        the fence engaged (False: ``new_epoch`` is not newer)."""
        with self._lock:
            if new_epoch <= self._epoch:
                return False
            if self._fenced_by is None or new_epoch > self._fenced_by:
                self._fenced_by = int(new_epoch)
                self._persist_fence_locked()
            return True

    def _raise_if_fenced(self) -> None:
        # caller holds self._lock; sits beside _raise_if_committer_dead
        # at the top of every append-side entry point
        if self._fenced_by is None:
            return
        self.fence_rejected_appends += 1
        self._persist_fence_locked()
        if _trace.ENABLED:
            now = time.perf_counter()
            _trace.evt("fence_reject", now, 0.0, track="wal",
                       args={"kind": "append", "epoch": self._epoch,
                             "fenced_by": self._fenced_by})
        raise FencedWrite(
            f"WAL epoch {self._epoch} fenced by epoch "
            f"{self._fenced_by}: this writer is a zombie ex-leader; "
            f"its appends are rejected, never merged")

    def _persist_fence_locked(self) -> None:
        # best-effort sidecar for offline tooling; never fails a write
        # path over telemetry
        try:
            import json
            tmp = os.path.join(self.wal_dir, _FENCE_STATE_FILE + ".tmp")
            with open(tmp, "w") as f:
                json.dump({"schema": FENCE_STATE_SCHEMA,
                           "epoch": self._epoch,
                           "fenced_by": self._fenced_by,
                           "rejected_appends": self.fence_rejected_appends},
                          f)
            os.replace(tmp, os.path.join(self.wal_dir, _FENCE_STATE_FILE))
        except OSError:
            pass

    def _request_durable(self, lsn: int) -> None:
        # caller holds self._lock: hand the barrier to the committer,
        # or serve it inline when there is none
        if self._committer is None:
            if self._synced_lsn < lsn:
                self._fsync()
            return
        now = time.perf_counter()
        self._io_q.append(("fsync", lsn, now))
        self._fsync_q.append((lsn, now))
        self._commit_cv.notify()

    def wait_durable(self, lsn: int,
                     timeout: Optional[float] = None) -> None:
        """Block until ``lsn`` is covered by the policy's durability
        promise (see the module docstring table). Raises the committer's
        death cause if the write/fsync can no longer happen.

        ``timeout`` (seconds) bounds the wait: on expiry a
        :class:`TimeoutError` is raised WITHOUT consuming the durability
        request — the committer keeps working, the frame may still
        become durable later, and a re-wait on the same LSN can succeed.
        This is the escape hatch for callers parked behind a wedged
        committer (a disk stall, a dead fd) who would otherwise hang
        forever."""
        if lsn <= 0:
            return
        deadline = (None if timeout is None
                    else time.perf_counter() + timeout)
        with self._lock:
            if self._committer is None and self._durable_point() < lsn:
                if self.fsync_policy != "os":
                    self._fsync()
            while self._durable_point() < lsn:
                if self.committer_error is not None:
                    raise self.committer_error
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"lsn {lsn} not durable after {timeout}s "
                            f"(durable point {self._durable_point()}, "
                            f"committer queue {len(self._io_q)})")
                self._durable_cv.wait(timeout=remaining)

    def when_durable(self, lsn: int,
                     fn: Callable[[Optional[BaseException]], None]) -> bool:
        """Register a continuation for ``lsn``: returns False when the
        LSN is already durable (the caller runs its continuation
        inline); otherwise ``fn(None)`` fires once the watermark passes
        it — in LSN order, under the WAL lock, on the thread that
        advanced the watermark — or ``fn(error)`` if the committer dies
        first. The serve frontend's deferred ticket resolution hangs off
        this seam."""
        with self._lock:
            if self.committer_error is not None:
                raise self.committer_error
            if lsn <= self._durable_point():
                return False
            self._callbacks.append((lsn, fn))
            return True

    def _fire_due_callbacks(self) -> None:
        # caller holds self._lock; a watermark just advanced
        point = self._durable_point()
        while self._callbacks and self._callbacks[0][0] <= point:
            _lsn, fn = self._callbacks.popleft()
            fn(None)

    def _advance_synced(self, cover: int) -> None:
        # caller holds self._lock
        self._synced_lsn = cover
        while self._lsn_pos and self._lsn_pos[0][0] <= cover:
            _lsn, seg, end = self._lsn_pos.popleft()
            self._synced_pos = LogPosition(seg, end)
        while self._fsync_q and self._fsync_q[0][0] <= cover:
            self._fsync_q.popleft()
        self._durable_cv.notify_all()
        self._fire_due_callbacks()

    def drain(self) -> None:
        """Block until the committer has performed every write and
        rotation enqueued so far (NO fsync barrier — use :meth:`sync`
        for that): afterwards the on-disk log matches what a process
        death at this instant would leave behind. A no-op with the
        inline committer, where nothing is ever deferred."""
        with self._lock:
            if self._io_q:
                self._commit_cv.notify()  # defensive wakeup
            while self._io_q or self._io_busy:
                if self.committer_error is not None:
                    raise self.committer_error
                self._durable_cv.wait()

    def _committer_loop(self) -> None:
        try:
            while True:
                with self._lock:
                    self._io_busy = False
                    self._durable_cv.notify_all()
                    while not self._io_q and not self._closing:
                        self._commit_cv.wait()
                    if not self._io_q:
                        return  # closing and caught up
                    self._io_busy = True
                    items = list(self._io_q)
                    self._io_q.clear()
                    f = self._f
                # the syscalls below run with _lock RELEASED — appends,
                # and the pump dispatching the next window through them,
                # keep flowing while this thread blocks in the kernel.
                # Only the committer writes in thread mode, so the fd is
                # stable here except across its own rotate commands.
                flushed_to = 0
                sync_target = 0
                for item in items:
                    kind = item[0]
                    if kind == "frame":
                        _kind, data, lsn = item
                        self._crash_point("wal_before_write")
                        f.write(data)
                        # page cache floor: flush per drain batch below
                        flushed_to = lsn
                        self._crash_point("wal_after_write")
                    elif kind == "rotate":
                        _kind, new_seq, cover = item
                        f.flush()
                        t0 = time.perf_counter()
                        with self._sync_lock:
                            # reflow-lint: waive lock-blocking-call -- wal.sync exists to serialize fsync/close; never taken on the admit path
                            os.fsync(f.fileno())
                            f.close()
                        f = open(_seg_path(self.wal_dir, new_seq), "wb")
                        f.write(_MAGIC)
                        f.flush()
                        with self._lock:
                            self._f = f
                            self._rotated_seq = new_seq
                            self.fsyncs += 1
                            self.fsync_s.append(time.perf_counter() - t0)
                            if flushed_to > self._flushed_lsn:
                                self._flushed_lsn = flushed_to
                            # bytes in a sealed segment are durable
                            # whatever the policy
                            if cover > self._synced_lsn:
                                self._advance_synced(cover)
                            else:
                                self._durable_cv.notify_all()
                    else:  # "fsync" durability request
                        _kind, lsn, _t = item
                        sync_target = max(sync_target, lsn)
                if flushed_to:
                    f.flush()
                do_sync = False
                with self._lock:
                    if flushed_to > self._flushed_lsn:
                        self._flushed_lsn = flushed_to
                        self._durable_cv.notify_all()
                        if self.fsync_policy == "os":
                            self._fire_due_callbacks()
                    if sync_target:
                        self._crash_point("wal_before_fsync")
                        if sync_target > self._synced_lsn:
                            # snapshot: every frame <= cover is written+
                            # flushed to fd ``f``, so an fsync started
                            # after this point durably covers them all
                            do_sync = True
                            cover = self._flushed_lsn
                            n = self._unsynced_appends
                            self._unsynced_appends = 0
                        else:
                            # free ride: a rotation seal or an earlier
                            # fsync already covered this barrier
                            self._crash_point("wal_after_fsync")
                if not do_sync:
                    continue
                t0 = time.perf_counter()
                with self._sync_lock:
                    if not f.closed:
                        # reflow-lint: waive lock-blocking-call -- the committer's durability fsync; wal.sync is the fsync-serializing leaf
                        os.fsync(f.fileno())
                dur = time.perf_counter() - t0
                with self._lock:
                    self.fsyncs += 1
                    self.fsync_s.append(dur)
                    if _trace.ENABLED:
                        _trace.evt("wal_fsync", t0, dur,
                                   track="wal-committer",
                                   args={"covered": n,
                                         "queue_depth": len(self._io_q)})
                    if n:
                        self.group_sizes.append(n)
                    if cover > self._synced_lsn:
                        self._advance_synced(cover)
                    self._crash_point("wal_after_fsync")
        except BaseException as e:  # noqa: BLE001 - incl. CrashPoint kills
            with self._lock:
                self.committer_error = e
                self._io_busy = False
                self._io_q.clear()
                self._fsync_q.clear()
                cbs = list(self._callbacks)
                self._callbacks.clear()
                self._durable_cv.notify_all()
            for _lsn, fn in cbs:
                fn(e)

    def restart_committer(self) -> bool:
        """Respawn a dead committer thread on a FRESH segment — the
        control plane's respawn-or-fail-fast actuator. Returns True when
        a restart happened (False: committer alive, inline mode, or the
        log is closing).

        Contract: committer death already failed every unacknowledged
        frame — queued writes were dropped, ``when_durable`` callbacks
        fired with the death cause, ``wait_durable`` waiters raised — so
        from every caller's perspective those LSNs are settled losses,
        exactly like a process crash losing unacknowledged batches
        (upstream re-send + replay dedup carries exactly-once across
        it). The restart therefore advances the durable watermarks to
        the written watermark and starts clean: the on-disk log simply
        never contains the lost frames. The old segment's tail is
        repaired first (the dead committer may have torn a frame
        mid-write), so sealed-segment scans stay valid."""
        with self._lock:
            if (self._committer is None or self._closing
                    or self.committer_error is None):
                return False
            self.last_committer_error = self.committer_error
            # seal best-effort and never append to the old fd again: a
            # torn tail must stay in the OLD segment where repair can
            # truncate it, same rule as a process restart
            try:
                with self._sync_lock:
                    if self._f is not None and not self._f.closed:
                        self._f.close()
            except OSError:
                pass
            segs = list_segments(self.wal_dir)
            if segs:
                _repair_tail(segs[-1][1], segs[-1][0])
                self._seq = segs[-1][0] + 1
            else:
                self._seq += 1
            self._open_segment()
            self._rotated_seq = self._seq
            # settle the watermarks: nothing below _written_lsn can ever
            # reach the disk now, and every such frame was already
            # reported failed to its caller
            self._flushed_lsn = self._written_lsn
            self._synced_lsn = self._written_lsn
            # the dropped frames never reached the disk: the shippable
            # prefix restarts at the fresh segment, never mid-loss
            self._lsn_pos.clear()
            self._synced_pos = LogPosition(self._seq, len(_MAGIC))
            self._unsynced_appends = 0
            self._io_q.clear()
            self._fsync_q.clear()
            self._io_busy = False
            self.committer_error = None
            self.committer_restarts += 1
            self._committer = threading.Thread(
                target=self._committer_loop, name="reflow-wal-committer",
                daemon=True)
            self._committer.start()
            self._durable_cv.notify_all()
            return True

    def _fsync(self) -> None:
        # inline barrier — caller holds self._lock AND owns a drained
        # log (inline committer always; thread mode only after the
        # committer has exited or via the close path), so everything
        # appended is written+flushed and this fsync covers through
        # _written_lsn. The _sync_lock round-trip serializes against a
        # committer fsync in flight on the same fd.
        t0 = time.perf_counter()
        with self._sync_lock:
            # reflow-lint: waive lock-blocking-call -- seal-path fsync; wal.sync only ever guards fsync/close
            os.fsync(self._f.fileno())
        self.fsyncs += 1
        self.fsync_s.append(time.perf_counter() - t0)
        if _trace.ENABLED:
            _trace.evt("wal_fsync", t0, time.perf_counter() - t0,
                       track="wal",
                       args={"covered": self._unsynced_appends,
                             "queue_depth": len(self._io_q)})
        if self._unsynced_appends:
            self.group_sizes.append(self._unsynced_appends)
            self._unsynced_appends = 0
        self._flushed_lsn = self._written_lsn
        self._advance_synced(self._written_lsn)

    def note_tick(self, *, wait: bool = True) -> None:
        """Tick-boundary durability barrier (``"tick"`` policy requests
        its fsync here; ``"record"`` already did; ``"os"`` never does).
        Skipped entirely when nothing was appended since the last
        barrier — an idle tick must not pay a no-op fsync."""
        if self.fsync_policy != "tick":
            return
        with self._lock:
            self._raise_if_fenced()
            self._raise_if_committer_dead()
            if self._synced_lsn >= self._written_lsn:
                return
            lsn = self._written_lsn
            self._request_durable(lsn)
        if wait:
            self.wait_durable(lsn)

    def sync(self) -> None:
        """Unconditional durability barrier (checkpoint path): blocks
        until everything appended so far is written AND fsynced,
        whatever the policy."""
        with self._lock:
            if self._committer is not None:
                self._raise_if_committer_dead()
                lsn = self._written_lsn
                if self._synced_lsn >= lsn:
                    return
                now = time.perf_counter()
                self._io_q.append(("fsync", lsn, now))
                self._fsync_q.append((lsn, now))
                self._commit_cv.notify()
                while self._synced_lsn < lsn:
                    if self.committer_error is not None:
                        raise self.committer_error
                    self._durable_cv.wait()
                return
            self._f.flush()
            self._fsync()

    def position(self) -> LogPosition:
        """Position one past the last appended byte (exact even while
        frames are still queued for the committer — offsets are
        assigned at append time)."""
        with self._lock:
            return LogPosition(self._seq, self._offset)

    def synced_position(self) -> LogPosition:
        """Byte-position twin of the *synced* watermark: every frame
        strictly before this (segment, offset) is written AND fsynced
        (power-loss durable). This is the prefix a shipper
        (``wal/ship.py``) may stream to read replicas — bytes past it
        may still be sitting in the committer queue or the page cache,
        and a power loss could take them back."""
        with self._lock:
            return self._synced_pos

    def rotate(self) -> None:
        """Seal the current segment and open the next one. The sealed
        segment is fsynced before close — whatever the policy, bytes in
        a sealed segment are durable (so the free-ride check can trust
        the durable watermark across rotations, and a mid-tick rotation
        can't strand committed records in the page cache). With the
        threaded committer this enqueues a rotate command and blocks
        until the committer has performed it (FIFO order keeps every
        already-queued frame in the old segment)."""
        with self._lock:
            if self._committer is not None:
                self._raise_if_committer_dead()
                self._seq += 1
                new_seq = self._seq
                self._io_q.append(("rotate", new_seq, self._written_lsn))
                self._offset = len(_MAGIC)
                self._commit_cv.notify()
                while self._rotated_seq < new_seq:
                    if self.committer_error is not None:
                        raise self.committer_error
                    self._durable_cv.wait()
                return
            self._f.flush()
            self._fsync()
            # the close rides the same mutex: a committer fsync holding
            # a stale snapshot of this fd must finish (or see .closed)
            # before the fd number can be reused by the next segment
            with self._sync_lock:
                self._f.close()
            self._seq += 1
            self._open_segment()

    def truncate_until(self, pos: LogPosition) -> List[str]:
        """Delete sealed segments strictly before ``pos.segment`` (the
        checkpoint already covers them). Returns the removed paths."""
        removed = []
        for seq, path in list_segments(self.wal_dir):
            if seq < pos.segment and seq != self._seq:
                os.remove(path)
                removed.append(path)
        return removed

    def publish_metrics(self, registry=None, *, name: str = "wal"
                        ) -> str:
        """Register this log's live summary (the ``summarize_wal``
        schema: append/fsync latency percentiles, group-commit shape)
        plus the committer pipeline gauges (``.queue_depth`` backlog of
        frames + barriers, ``.durable_lag_s`` age of the oldest pending
        durability request) as obs metric sources. Returns the source
        key."""
        from reflow_tpu.obs import REGISTRY
        from reflow_tpu.utils.metrics import summarize_wal
        reg = registry if registry is not None else REGISTRY
        reg.register_source(name,
                            lambda: summarize_wal(self).to_dict())
        reg.gauge(f"{name}.fsync_rate",
                  lambda: self.fsyncs / max(self.appends, 1))
        reg.gauge(f"{name}.queue_depth", self.queue_depth)
        reg.gauge(f"{name}.durable_lag_s", self.durable_lag_s)
        self._metric_keys.append((reg, name))
        return name

    def close(self) -> None:
        # stop the committer first: it drains every queued frame and
        # barrier (firing their continuations) before exiting, so no
        # ticket is stranded by a clean shutdown
        committer = self._committer
        if committer is not None:
            with self._lock:
                self._closing = True
                self._commit_cv.notify_all()
            committer.join(timeout=30.0)
            self._committer = None
        with self._lock:
            if self._f is not None and not self._f.closed:
                self._f.flush()
                # seal-time idle skip: only fsync when bytes were
                # appended since the last durability barrier
                if self._synced_lsn < self._written_lsn \
                        or self._unsynced_appends:
                    self._fsync()
                with self._sync_lock:
                    self._f.close()
            # a committer that died mid-pipeline already failed its
            # callbacks; a clean close must not strand any either
            if self._callbacks:
                self._fire_due_callbacks()
                self._callbacks.clear()
        for reg, key in self._metric_keys:
            reg.unregister_source(key)
            reg.unregister_prefix(f"{key}.")
        self._metric_keys = []


# -- read side -------------------------------------------------------------

def _valid_prefix(data: bytes) -> int:
    """Byte length of the longest valid record prefix (past the magic);
    -1 when even the magic is gone."""
    if data[:len(_MAGIC)] != _MAGIC:
        return -1
    off = len(_MAGIC)
    while off + _HEADER.size <= len(data):
        length, crc = _HEADER.unpack_from(data, off)
        payload = data[off + _HEADER.size: off + _HEADER.size + length]
        if (length > _MAX_RECORD or len(payload) < length
                or zlib.crc32(payload) != crc):
            break
        off += _HEADER.size + length
    return off


def _repair_tail(path: str, seq: int) -> Optional[TornTail]:
    """Truncate ``path`` to its valid record prefix (drop a torn final
    record); delete it outright if even the magic header is torn.
    Returns what was repaired, or None for an already-clean segment."""
    with open(path, "rb") as f:
        data = f.read()
    keep = _valid_prefix(data)
    if keep == len(data):
        return None
    if keep < 0:
        os.remove(path)
        return TornTail(seq, 0, "segment magic torn; segment removed")
    with open(path, "rb+") as f:
        f.truncate(keep)
    return TornTail(seq, keep,
                    f"torn record truncated ({len(data) - keep} bytes)")

def _read_segment(path: str, seq: int, is_last: bool,
                  ) -> Tuple[List[Tuple[LogPosition, Dict]],
                             Optional[TornTail]]:
    records: List[Tuple[LogPosition, Dict]] = []

    def bad(offset: int, reason: str):
        if is_last:
            return records, TornTail(seq, offset, reason)
        raise WalError(f"{path} @ {offset}: {reason} in a sealed "
                       f"(non-final) segment — real corruption, not a "
                       f"torn tail")

    with open(path, "rb") as f:
        data = f.read()
    if data[:len(_MAGIC)] != _MAGIC:
        return bad(0, f"bad segment magic {data[:len(_MAGIC)]!r}")
    off = len(_MAGIC)
    while off < len(data):
        if off + _HEADER.size > len(data):
            return bad(off, "truncated frame header")
        length, crc = _HEADER.unpack_from(data, off)
        if length > _MAX_RECORD:
            return bad(off, f"implausible frame length {length}")
        payload = data[off + _HEADER.size: off + _HEADER.size + length]
        if len(payload) < length:
            return bad(off, f"truncated payload ({len(payload)}/{length} "
                            f"bytes)")
        if zlib.crc32(payload) != crc:
            return bad(off, "CRC mismatch")
        try:
            record = pickle.loads(payload)
        except Exception as e:  # noqa: BLE001 - framed+CRC-clean yet unloadable
            return bad(off, f"unpicklable payload ({e})")
        records.append((LogPosition(seq, off), record))
        off += _HEADER.size + length
    return records, None


def scan_wal(wal_dir: str, start: Optional[Tuple[int, int]] = None,
             ) -> Tuple[List[Tuple[LogPosition, Dict]], Optional[TornTail]]:
    """Parse every record at or after ``start`` ((segment, offset), e.g.
    a checkpoint's recorded position). Returns ``(records, torn)`` where
    ``torn`` describes a tolerated torn tail in the final segment (None
    for a clean log). Raises :class:`WalError` on non-tail corruption.
    """
    segs = list_segments(wal_dir)
    records: List[Tuple[LogPosition, Dict]] = []
    torn: Optional[TornTail] = None
    for ix, (seq, path) in enumerate(segs):
        if start is not None and seq < start[0]:
            continue
        seg_records, torn = _read_segment(path, seq, ix == len(segs) - 1)
        for pos, rec in seg_records:
            if start is not None and pos.segment == start[0] \
                    and pos.offset < start[1]:
                continue
            records.append((pos, rec))
    return records, torn


def iter_push_records(records: Iterable[Tuple[LogPosition, Dict]]):
    """The push records of a scan, in log order."""
    for pos, rec in records:
        if rec.get("kind") == "push":
            yield pos, rec
