"""Write-ahead delta log: durable exactly-once ingestion (docs/guide.md
"Durability and delivery").

The checkpoint module snapshots state *at* a save point; everything
pushed since is, per its own docstring, "the user's responsibility to
replay". This package closes that gap: every accepted source batch is
appended to a segmented, CRC-framed log *before* the scheduler accepts
it, so a process crash between checkpoints loses nothing. Recovery
loads the latest checkpoint and replays the log tail through the
scheduler's existing ``push(batch_id=...)`` dedup — replay is
idempotent by construction, so exactly-once survives process death,
torn tail writes, and crashes between ``push`` and ``tick``.
"""

from reflow_tpu.wal.compact import WalCompactor, read_compact_manifest
from reflow_tpu.wal.durable import DurableScheduler
from reflow_tpu.wal.log import (FencedWrite, LogPosition, WalError,
                                WriteAheadLog, scan_wal)
from reflow_tpu.wal.recovery import RecoveryReport, recover, replay_records
from reflow_tpu.wal.ship import (SegmentShipper, ShipAck, Shipment,
                                 ShipNack)

__all__ = [
    "DurableScheduler",
    "FencedWrite",
    "LogPosition",
    "RecoveryReport",
    "SegmentShipper",
    "ShipAck",
    "ShipNack",
    "Shipment",
    "WalCompactor",
    "WalError",
    "WriteAheadLog",
    "read_compact_manifest",
    "recover",
    "replay_records",
    "scan_wal",
]
