"""Exception-policy pass: no bare ``assert`` on runtime paths.

The PR-1 rule, now machine-enforced: ``python -O`` strips ``assert``
statements, so an invariant guarded by one silently stops being
checked in optimized deployments — and a tripped assert raises
``AssertionError`` with no context instead of the typed error the
caller could handle. Runtime code (everything under ``reflow_tpu/``
except the analysis package itself) must raise a real exception.

Tests are exempt (pytest rewrites asserts into rich diffs — there they
are the right tool), as are asserts inside ``TYPE_CHECKING`` blocks.
"""

from __future__ import annotations

import ast
from typing import List

from reflow_tpu.analysis.core import Corpus, Finding, register_pass

RULES = {
    "bare-assert": "runtime code must raise typed errors, not assert "
                   "(python -O strips them)",
}


@register_pass("exceptions", RULES)
def exception_pass(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.under("reflow_tpu/"):
        if sf.tree is None or sf.path.startswith("reflow_tpu/analysis/"):
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assert):
                findings.append(Finding(
                    "bare-assert", sf.path, node.lineno,
                    "bare assert on a runtime path — raise a typed "
                    "error instead (python -O strips asserts)"))
    return findings
