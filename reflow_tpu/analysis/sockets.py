"""Socket-timeout pass: no blocking socket call without a deadline.

The replication transport's contract (``net/transport.py``) is that no
wire operation can wait forever — a partitioned peer must surface as a
:class:`~reflow_tpu.net.framing.TransportError` on a bounded clock, not
as a thread parked in ``recv`` until the heat death of the pod. One
rule machine-checks it:

- **socket-no-timeout** — a ``recv``/``recvfrom``/``accept``/
  ``connect`` call in ``reflow_tpu/`` whose enclosing function never
  arms a deadline: no ``settimeout(...)`` call, and not a
  ``socket.create_connection(..., timeout=...)``. Scoped to files that
  actually ``import socket`` so unrelated objects with a ``connect``
  method (schedulers, clients) don't trip it.

The check is per enclosing function on purpose: that is the unit in
which a deadline discipline is visible to a reader, and the transport
code re-arms ``settimeout`` before every blocking call precisely so
each function is self-evidently bounded. Genuinely-blocking intent
(rare, e.g. a tool that wants to wait forever) takes the standard
waiver with a reason.
"""

from __future__ import annotations

import ast
from typing import List

from reflow_tpu.analysis.core import Corpus, Finding, register_pass

RULES = {
    "socket-no-timeout": "blocking socket call with no settimeout/"
                         "timeout= in its enclosing function",
}

#: blocking socket operations that honor the socket's timeout
_BLOCKING = {"recv", "recvfrom", "recv_into", "accept", "connect"}


def _imports_socket(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "socket" or a.name.startswith("socket.")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module == "socket":
                return True
    return False


def _has_deadline(fn: ast.AST) -> bool:
    """Does this function arm any socket deadline? True on a
    ``settimeout`` call or a ``create_connection(..., timeout=...)``."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if attr == "settimeout":
            return True
        if attr == "create_connection" \
                and any(kw.arg == "timeout" for kw in node.keywords):
            return True
    return False


@register_pass("sockets", RULES)
def socket_pass(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.under("reflow_tpu/"):
        if sf.tree is None or not _imports_socket(sf.tree):
            continue
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            armed = _has_deadline(fn)
            if armed:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) \
                        or not isinstance(node.func, ast.Attribute):
                    continue
                attr = node.func.attr
                if attr not in _BLOCKING:
                    continue
                if attr == "create_connection":
                    continue  # handled by _has_deadline
                if attr == "connect" \
                        and any(kw.arg == "timeout"
                                for kw in node.keywords):
                    continue
                findings.append(Finding(
                    "socket-no-timeout", sf.path, node.lineno,
                    f".{attr}() with no settimeout() in "
                    f"{fn.name}() — a partitioned peer would park "
                    f"this thread forever; arm a deadline (see "
                    f"net/transport.py) or waive with the blocking "
                    f"intent spelled out"))
    return findings
