"""Metrics-hygiene pass: registration pairing, naming, and release
coverage.

- **metrics-unpaired** — a file that registers metric sources or gauges
  (``register_source(...)`` / ``reg.gauge(...)``) must also contain an
  unregister path (``unregister_source`` / ``unregister_prefix``).
  Sources and gauges hold lambdas that capture ``self``; a close/seal
  that does not unregister leaves the registry reading a dead object
  forever (and pins it in memory). The check is per-file by design:
  the unregister belongs next to the register (``publish_metrics`` /
  ``close`` live on the same class), not in some caller.
- **metrics-name** — metric name literals must be dotted lower_snake
  (``wal.fsync_rate``, ``serve.<graph>.depth``): one grammar means
  ``unregister_prefix(f"{key}.")`` and dashboards can rely on the
  separator. F-string names are checked on their literal fragments.
- **metrics-registry-mismatch** — a file whose registrations target a
  caller-supplied registry (the ``publish_metrics(registry=None)``
  convention binds it to ``reg``) while EVERY unregister in the file
  goes through the module-global ``REGISTRY``. The pairing rule above
  is satisfied, but gauges registered into a private registry (the
  fleet telemetry plane gives every node its own) are never released —
  exactly the leak shipped in the pre-fleet ``ReadTier``/``ship``
  close paths. The fix convention: store ``(reg, name)`` pairs and
  release on the registry that registered.
- **metrics-source-unreleased** — corpus-wide ``register_source``
  coverage: every ``register_source`` call anywhere in the tree (not
  just ``reflow_tpu/``) must be releasable — an
  ``unregister_source``/``unregister_prefix`` in the same file, or,
  for literal keys, a literal release fragment somewhere in the corpus
  that covers the key. Cross-file on purpose: a source registered by
  one module and sealed by another still counts, and a source nobody
  releases is a leak no per-file view can see. Files already flagged
  ``metrics-unpaired`` are not flagged again for the same leak.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Tuple

from reflow_tpu.analysis.core import Corpus, Finding, register_pass

_NAME_FRAG = re.compile(r"^[a-z0-9_.]*$")

RULES = {
    "metrics-unpaired": "register_source/gauge without an unregister "
                        "path in the same file",
    "metrics-name": "metric names must be dotted lower_snake",
    "metrics-registry-mismatch": "registrations on a caller-supplied "
                                 "registry but every unregister "
                                 "targets the global REGISTRY",
    "metrics-source-unreleased": "a register_source with no covering "
                                 "unregister anywhere in the corpus",
}

_REGISTERING = ("register_source", "gauge", "counter")
_UNREGISTERING = ("unregister_source", "unregister_prefix")

#: files the rules never apply to: the registry defines the API (it
#: can't pair it) and the analysis package only names the calls
_EXEMPT = ("reflow_tpu/analysis/", "reflow_tpu/obs/registry.py")


def _name_fragments(arg: ast.expr) -> List[str]:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.JoinedStr):
        return [str(p.value) for p in arg.values
                if isinstance(p, ast.Constant)]
    return []


def _leading_literal(arg: ast.expr) -> Optional[str]:
    """The key's leading literal text, or None for a fully dynamic
    name (``register_source(key, ...)``)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr) and arg.values and isinstance(
            arg.values[0], ast.Constant):
        return str(arg.values[0].value)
    return None


def _receiver(call: ast.Call) -> Optional[str]:
    """The dotted receiver of ``recv.method(...)`` — ``"reg"``,
    ``"REGISTRY"``, ``"self.registry"`` — or None for a bare name."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    parts: List[str] = []
    v = f.value
    while isinstance(v, ast.Attribute):
        parts.append(v.attr)
        v = v.value
    if not isinstance(v, ast.Name):
        return None
    parts.append(v.id)
    return ".".join(reversed(parts))


def _calls(sf) -> Tuple[List[ast.Call], List[ast.Call]]:
    """(registering, unregistering) calls in one file."""
    registers: List[ast.Call] = []
    unregisters: List[ast.Call] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if attr in _REGISTERING and node.args:
            registers.append(node)
        elif attr in _UNREGISTERING:
            unregisters.append(node)
    return registers, unregisters


@register_pass("metrics", RULES)
def metrics_pass(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    unpaired_paths = set()  # already reported: don't double-flag below
    for sf in corpus.under("reflow_tpu/"):
        if sf.tree is None or sf.path.startswith(_EXEMPT):
            continue
        registers, unregisters = _calls(sf)
        for node in registers:
            for frag in _name_fragments(node.args[0]):
                if not _NAME_FRAG.match(frag):
                    findings.append(Finding(
                        "metrics-name", sf.path, node.lineno,
                        f"metric name fragment {frag!r} is not "
                        f"dotted lower_snake"))
        if registers and not unregisters:
            n = registers[0]
            unpaired_paths.add(sf.path)
            findings.append(Finding(
                "metrics-unpaired", sf.path, n.lineno,
                f"{len(registers)} metric registration(s) but no "
                f"unregister_source/unregister_prefix in this file — "
                f"the close/seal path must drop them or the registry "
                f"keeps reading a dead object"))
        reg_recvs = {_receiver(n) for n in registers}
        unreg_recvs = [_receiver(n) for n in unregisters]
        if (unreg_recvs
                and any(r not in (None, "REGISTRY") for r in reg_recvs)
                and all(r == "REGISTRY" for r in unreg_recvs)):
            n = registers[0]
            findings.append(Finding(
                "metrics-registry-mismatch", sf.path, n.lineno,
                f"registrations target "
                f"{sorted(r for r in reg_recvs if r)} but every "
                f"unregister goes through the global REGISTRY — "
                f"metrics registered into a caller-supplied registry "
                f"are never released; store (registry, name) pairs "
                f"and release on the registry that registered"))

    # -- corpus-wide register_source coverage (cross-file on purpose) --
    release_frags = set()
    files_with_release = set()
    sources: List[Tuple[object, ast.Call]] = []
    for sf in corpus.files.values():
        if sf.tree is None or sf.path.startswith(_EXEMPT) \
                or sf.path.startswith("tests/"):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr == "register_source" and node.args:
                sources.append((sf, node))
            elif attr in _UNREGISTERING:
                files_with_release.add(sf.path)
                if node.args:
                    lit = _leading_literal(node.args[0])
                    if lit:
                        release_frags.add(lit)
    for sf, node in sources:
        if sf.path in files_with_release:
            continue  # per-file pairing, the normal convention
        if sf.path in unpaired_paths:
            continue  # metrics-unpaired already flagged this file
        key = _leading_literal(node.args[0])
        covered = key is not None and any(
            key == frag or key.startswith(frag)
            or frag.startswith(key) for frag in release_frags)
        if not covered:
            findings.append(Finding(
                "metrics-source-unreleased", sf.path, node.lineno,
                f"register_source({key!r}) has no unregister in this "
                f"file and no covering unregister literal anywhere in "
                f"the corpus — the source outlives its object"))
    return findings
