"""Metrics-hygiene pass: registration pairing and naming.

- **metrics-unpaired** — a file that registers metric sources or gauges
  (``register_source(...)`` / ``reg.gauge(...)``) must also contain an
  unregister path (``unregister_source`` / ``unregister_prefix``).
  Sources and gauges hold lambdas that capture ``self``; a close/seal
  that does not unregister leaves the registry reading a dead object
  forever (and pins it in memory). The check is per-file by design:
  the unregister belongs next to the register (``publish_metrics`` /
  ``close`` live on the same class), not in some caller.
- **metrics-name** — metric name literals must be dotted lower_snake
  (``wal.fsync_rate``, ``serve.<graph>.depth``): one grammar means
  ``unregister_prefix(f"{key}.")`` and dashboards can rely on the
  separator. F-string names are checked on their literal fragments.
"""

from __future__ import annotations

import ast
import re
from typing import List

from reflow_tpu.analysis.core import Corpus, Finding, register_pass

_NAME_FRAG = re.compile(r"^[a-z0-9_.]*$")

RULES = {
    "metrics-unpaired": "register_source/gauge without an unregister "
                        "path in the same file",
    "metrics-name": "metric names must be dotted lower_snake",
}

_REGISTERING = ("register_source", "gauge", "counter")
_UNREGISTERING = ("unregister_source", "unregister_prefix")


def _name_fragments(arg: ast.expr) -> List[str]:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [arg.value]
    if isinstance(arg, ast.JoinedStr):
        return [str(p.value) for p in arg.values
                if isinstance(p, ast.Constant)]
    return []


@register_pass("metrics", RULES)
def metrics_pass(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    for sf in corpus.under("reflow_tpu/"):
        if sf.tree is None or sf.path.startswith((
                "reflow_tpu/analysis/", "reflow_tpu/obs/registry.py")):
            continue  # the registry defines the API; it can't pair it
        registers: List[ast.Call] = []
        unregisters = 0
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr in _REGISTERING and node.args:
                registers.append(node)
                for frag in _name_fragments(node.args[0]):
                    if not _NAME_FRAG.match(frag):
                        findings.append(Finding(
                            "metrics-name", sf.path, node.lineno,
                            f"metric name fragment {frag!r} is not "
                            f"dotted lower_snake"))
            elif attr in _UNREGISTERING:
                unregisters += 1
        if registers and not unregisters:
            n = registers[0]
            findings.append(Finding(
                "metrics-unpaired", sf.path, n.lineno,
                f"{len(registers)} metric registration(s) but no "
                f"unregister_source/unregister_prefix in this file — "
                f"the close/seal path must drop them or the registry "
                f"keeps reading a dead object"))
    return findings
