"""reflow_tpu.analysis — project-specific static analysis (reflow-lint).

The serving stack's correctness rests on conventions no general linter
knows: lock acquisition order, crash-seam grammar and test coverage,
metrics register/unregister pairing, the env-knob registry, and the
no-bare-assert exception policy. This package machine-checks them.

Entry point: ``python tools/reflow_lint.py`` (``--json`` emits the
``reflow.lint/1`` schema). Library use::

    from reflow_tpu.analysis import run
    report = run("/path/to/repo")          # all fast passes
    report["findings"]                      # list of dicts

The runtime twin of the lock pass is ``REFLOW_LOCKCHECK=1`` +
``named_lock`` in :mod:`reflow_tpu.utils.runtime` — see docs/guide.md
"Static analysis & lockcheck".
"""

from reflow_tpu.analysis.core import (Corpus, Finding, PASSES, RULES,
                                      render_report, run, to_json)

__all__ = ["Corpus", "Finding", "PASSES", "RULES", "render_report",
           "run", "to_json"]
