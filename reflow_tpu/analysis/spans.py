"""Span-catalog pass: every emitted span kind must be documented.

`tools/trace_inspect.py`, the flight recorder, and every post-mortem
reader key off span *names* — an undocumented kind is a dashboard tile
nobody can interpret and a `--require-chain` link nobody knows to ask
for. One rule machine-checks the contract:

- **span-kind-undocumented** — a span kind emitted anywhere in
  ``reflow_tpu/`` (a string-literal first argument to
  ``trace.evt(...)``, an entry of ``obs.trace.STAGES``, or a flight
  ``note("...")`` event) must appear backticked in the span catalog of
  ``docs/guide.md``. Dynamic families (``f"control.{...}"``) are
  documented by their prefix — a backticked token starting with
  ``control.`` covers the family.

The check is name-level on purpose: the catalog is the single place a
reader maps a trace row to semantics, so the lint points at the emit
site and asks for one line of prose, not a waiver.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Set, Tuple

from reflow_tpu.analysis.core import Corpus, Finding, register_pass

RULES = {
    "span-kind-undocumented": "span kind emitted in reflow_tpu/ but "
                              "absent from the docs/guide.md span "
                              "catalog",
}

#: the documentation corpus the catalog lives in, repo-relative
_GUIDE = os.path.join("docs", "guide.md")

_BACKTICK = re.compile(r"`([^`\n]+)`")


def _doc_tokens(root: str) -> Optional[Set[str]]:
    try:
        text = open(os.path.join(root, _GUIDE),
                    encoding="utf-8", errors="replace").read()
    except OSError:
        return None
    return set(_BACKTICK.findall(text))


def _evt_name(call: ast.Call) -> Optional[Tuple[str, bool]]:
    """``(name, is_prefix)`` for a span-emitting call, else None.

    Matches ``evt("name", ...)`` / ``<mod>.evt("name", ...)`` and
    flight ``note("name", ...)`` / ``<mod>.note("name", ...)``. An
    f-string first argument yields its leading constant text as a
    prefix family (``f"control.{kind}"`` -> ``("control.", True)``).
    """
    f = call.func
    attr = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if attr not in ("evt", "note") or not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr) and arg.values \
            and isinstance(arg.values[0], ast.Constant) \
            and isinstance(arg.values[0].value, str):
        return arg.values[0].value, True
    return None


def _stage_names(tree: ast.AST) -> List[Tuple[str, int]]:
    """String elements of the module-level ``STAGES = (...)`` tuple —
    ``ticket_stages`` emits them through a variable the call-site scan
    can't see."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "STAGES"
                        for t in node.targets) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            for el in node.value.elts:
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, str):
                    out.append((el.value, el.lineno))
    return out


@register_pass("spans", RULES)
def span_pass(corpus: Corpus) -> List[Finding]:
    tokens = _doc_tokens(corpus.root)
    if tokens is None:
        return []  # no guide in this checkout; nothing to hold against
    findings: List[Finding] = []
    seen: Set[str] = set()

    def _check(name: str, is_prefix: bool, path: str, line: int) -> None:
        if name in seen:
            return
        seen.add(name)
        if is_prefix:
            ok = any(t.startswith(name) for t in tokens)
            what = f"span family `{name}*`"
        else:
            ok = name in tokens
            what = f"span kind `{name}`"
        if not ok:
            findings.append(Finding(
                "span-kind-undocumented", path, line,
                f"{what} is emitted here but not in the docs/guide.md "
                f"span catalog — add one backticked line saying what "
                f"it measures"))

    for sf in corpus.under("reflow_tpu/"):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                hit = _evt_name(node)
                if hit is not None:
                    _check(hit[0], hit[1], sf.path, node.lineno)
        if sf.path == "reflow_tpu/obs/trace.py":
            for name, line in _stage_names(sf.tree):
                _check(name, False, sf.path, line)
    return findings
