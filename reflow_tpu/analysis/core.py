"""reflow-lint core: the corpus walker, waiver grammar, pass registry,
and the ``reflow.lint/1`` JSON report.

Passes are whole-corpus functions — several rules are inherently
cross-file (a crash seam defined in ``serve/frontend.py`` is "tested"
by a string in ``tests/``; the lock held-before graph merges edges from
every module) — so the framework parses the tree once into a
:class:`Corpus` and hands the same object to every pass.

Waivers are inline and must carry a reason::

    os.fsync(fd)  # reflow-lint: waive lock-blocking-call -- fsync IS the
                  # committer's job; _sync_lock exists to serialize it

A waiver suppresses the named rule on its own line and the line it is
attached to (same line or the line directly above, so a finding on a
long statement can carry its waiver as a trailing or preceding
comment). A waiver without a ``-- reason`` is itself a finding
(``waiver-no-reason``): the whole point is that every suppression
explains itself to the next reader.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

#: directories the walker never descends into
SKIP_DIRS = {"__pycache__", ".git", ".claude", "node_modules", ".venv",
             "venv", "build", "dist", ".pytest_cache"}

_WAIVE_RE = re.compile(
    r"#\s*reflow-lint:\s*waive\s+([A-Za-z0-9_,-]+)(?:\s*--\s*(.*))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint violation, pointing at a source line."""

    rule: str
    path: str
    line: int
    msg: str

    def to_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "msg": self.msg}

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


@dataclasses.dataclass
class SourceFile:
    """One parsed file: text, line list, AST (None on syntax error),
    and the waiver map ``line -> set of waived rule names``."""

    path: str            # repo-relative, forward slashes
    text: str
    tree: Optional[ast.AST]
    waivers: Dict[int, set]
    bad_waivers: List[int]  # waiver comments missing a reason

    @property
    def lines(self) -> List[str]:
        return self.text.splitlines()


class Corpus:
    """Every python file under the repo root, parsed once."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        self.files: Dict[str, SourceFile] = {}
        for path in self._walk():
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            try:
                text = open(path, encoding="utf-8",
                            errors="replace").read()
            except OSError:
                continue
            try:
                tree = ast.parse(text, filename=rel)
            except SyntaxError:
                tree = None  # compileall owns syntax; don't double-report
            waivers, bad = _parse_waivers(text)
            self.files[rel] = SourceFile(rel, text, tree, waivers, bad)

    def _walk(self) -> List[str]:
        out: List[str] = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in SKIP_DIRS
                                 and not d.endswith(".egg-info"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
        return out

    def under(self, *prefixes: str) -> List[SourceFile]:
        """Files whose repo-relative path starts with any prefix."""
        return [f for p, f in sorted(self.files.items())
                if any(p == pre or p.startswith(pre.rstrip("/") + "/")
                       or (pre.endswith("/") and p.startswith(pre))
                       for pre in prefixes)]


def _parse_waivers(text: str) -> Tuple[Dict[int, set], List[int]]:
    waivers: Dict[int, set] = {}
    bad: List[int] = []
    for i, line in enumerate(text.splitlines(), start=1):
        m = _WAIVE_RE.search(line)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if not (m.group(2) or "").strip():
            bad.append(i)
        # the waiver covers its own line and the next (a comment line
        # directly above the flagged statement)
        for ln in (i, i + 1):
            waivers.setdefault(ln, set()).update(rules)
    return waivers, bad


# -- pass registry ----------------------------------------------------------

#: rule name -> one-line description (the ``--list-rules`` catalog)
RULES: Dict[str, str] = {
    "waiver-no-reason": "a waiver comment must carry `-- <reason>`",
}

#: pass name -> (callable(Corpus) -> List[Finding], rules it emits)
PASSES: Dict[str, Tuple[Callable[[Corpus], List[Finding]], List[str]]] = {}


def register_pass(name: str, rules: Dict[str, str]):
    """Decorator: register a corpus pass and the rules it can emit."""
    def deco(fn: Callable[[Corpus], List[Finding]]):
        RULES.update(rules)
        PASSES[name] = (fn, list(rules))
        return fn
    return deco


def _waived(corpus: Corpus, f: Finding) -> bool:
    sf = corpus.files.get(f.path)
    return bool(sf and f.rule in sf.waivers.get(f.line, ()))


def run(root: str, *, passes: Optional[List[str]] = None,
        rules: Optional[List[str]] = None) -> Dict[str, object]:
    """Run the selected passes over ``root``; returns the report dict
    (schema ``reflow.lint/1``). Findings on waived lines are dropped
    but counted; a waiver missing its reason is always a finding."""
    # passes self-register at import; import here so `import
    # reflow_tpu.analysis.core` alone stays side-effect-light
    from reflow_tpu.analysis import (constants, envknobs,  # noqa: F401
                                     exceptions, locks, metrics_pass,
                                     seams, sockets, spans)

    corpus = Corpus(root)
    findings: List[Finding] = []
    waived = 0
    selected = passes if passes is not None else sorted(PASSES)
    for name in selected:
        if name not in PASSES:
            raise KeyError(f"unknown pass {name!r}; have {sorted(PASSES)}")
        fn, _ = PASSES[name]
        for f in fn(corpus):
            if rules is not None and f.rule not in rules:
                continue
            if _waived(corpus, f):
                waived += 1
            else:
                findings.append(f)
    if rules is None or "waiver-no-reason" in rules:
        for sf in corpus.files.values():
            for ln in sf.bad_waivers:
                findings.append(Finding(
                    "waiver-no-reason", sf.path, ln,
                    "waiver without `-- <reason>`: say why it is safe"))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "schema": "reflow.lint/1",
        "root": corpus.root,
        "files_scanned": len(corpus.files),
        "passes": selected,
        "findings": [f.to_dict() for f in findings],
        "counts": counts,
        "waived": waived,
    }


def render_report(report: Dict[str, object]) -> str:
    lines = []
    for f in report["findings"]:
        lines.append(f"{f['path']}:{f['line']}: [{f['rule']}] {f['msg']}")
    n = len(report["findings"])
    lines.append(f"reflow-lint: {n} finding{'s' if n != 1 else ''} "
                 f"({report['waived']} waived) across "
                 f"{report['files_scanned']} files")
    return "\n".join(lines)


def to_json(report: Dict[str, object]) -> str:
    return json.dumps(report, indent=2, sort_keys=False)
