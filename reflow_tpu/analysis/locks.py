"""Lock-discipline pass: the static twin of the ``REFLOW_LOCKCHECK=1``
runtime monitor (utils/runtime.py).

Three rules, all keyed on the same ``named_lock("...")`` names the
runtime detector uses:

- **lock-unnamed** — a ``threading.Lock()`` / ``RLock()`` / bare
  ``Condition()`` created inside ``reflow_tpu/``. Every lock on a
  concurrent path must come from :func:`named_lock` so both detectors
  can see it (a raw lock is invisible to the held-before graph).
- **lock-order-cycle** — nested ``with``-acquisitions are merged into a
  whole-repo held-before graph over lock *names* (dynamic per-instance
  names like ``serve.replica.<n>`` collapse to their literal prefix +
  ``*``); any strongly-connected component is a potential AB/BA
  deadlock. One level of same-class call expansion is applied (a method
  called while a lock is held contributes the locks IT acquires), so
  the common "helper that takes the other lock" shape is visible.
- **lock-blocking-call** — a call that can block or dispatch for a long
  time (``os.fsync``, ``time.sleep``, ``Future.result``,
  ``wait_durable``, ``block_until_ready``, scheduler ``tick``/
  ``tick_many``/``run_window``/``dispatch_staged``, thread ``join``)
  made while a named lock is held. These turn a mutex into a latency
  cliff for every other thread parked on it.
- **lock-wait-no-loop** — ``Condition.wait()`` outside a ``while``
  predicate loop (spurious wakeups make a bare ``wait`` a correctness
  bug; ``wait_for`` carries its own loop and is always fine).

The analysis is intra-file and syntactic by design: it cannot see
locks passed across modules or acquired via callbacks — that is
exactly what the runtime monitor is for. The two share the name
vocabulary so a static finding and a runtime raise point at the same
graph node.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from reflow_tpu.analysis.core import Corpus, Finding, register_pass

#: attribute/function names that block (or dispatch a device program)
BLOCKING = {"fsync", "sleep", "result", "wait_durable",
            "block_until_ready", "tick", "tick_many", "run_window",
            "dispatch_staged"}

RULES = {
    "lock-unnamed": "locks in reflow_tpu/ must come from named_lock()",
    "lock-order-cycle": "nested lock acquisitions form an ordering cycle",
    "lock-blocking-call": "blocking/dispatch call while a lock is held",
    "lock-wait-no-loop": "Condition.wait() outside a while-predicate loop",
}


def _literal_prefix(node: ast.expr) -> Optional[str]:
    """The lock name for a named_lock() first argument: a constant
    string verbatim, an f-string collapsed to its literal prefix + '*'."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        head = ""
        for part in node.values:
            if isinstance(part, ast.Constant):
                head += str(part.value)
            else:
                return head + "*"
        return head
    if isinstance(node, ast.IfExp):  # f"..." if name else "..."
        a = _literal_prefix(node.body)
        return a if a is not None else _literal_prefix(node.orelse)
    return None


def _find_call(node: ast.expr, fn_name: str) -> Optional[ast.Call]:
    """The first call to ``fn_name`` anywhere inside ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            f = sub.func
            if (isinstance(f, ast.Name) and f.id == fn_name) or \
                    (isinstance(f, ast.Attribute) and f.attr == fn_name):
                return sub
    return None


class _ClassMap:
    """Per-class lock/condition attribute resolution."""

    def __init__(self) -> None:
        self.locks: Dict[str, str] = {}       # attr -> lock name
        self.conds: Dict[str, str] = {}       # cond attr -> lock name
        self.methods: Dict[str, ast.FunctionDef] = {}


def _scan_class(cls: ast.ClassDef, module_locks: Dict[str, str]
                ) -> _ClassMap:
    cm = _ClassMap()
    for fn in cls.body:
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cm.methods[fn.name] = fn
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                tgt = node.targets[0]
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                call = _find_call(node.value, "named_lock")
                if call is not None and call.args:
                    name = _literal_prefix(call.args[0])
                    if name:
                        cm.locks[tgt.attr] = name
                    continue
                call = _find_call(node.value, "Condition")
                if call is not None:
                    if call.args:
                        arg = call.args[0]
                        if (isinstance(arg, ast.Attribute)
                                and isinstance(arg.value, ast.Name)
                                and arg.value.id == "self"
                                and arg.attr in cm.locks):
                            cm.conds[tgt.attr] = cm.locks[arg.attr]
                        else:
                            cm.conds[tgt.attr] = f"<{tgt.attr}>"
                    # bare Condition() handled by the unnamed scan
    cm.locks.update({k: v for k, v in module_locks.items()
                     if k not in cm.locks})
    return cm


def _lock_name_of(expr: ast.expr, cm: Optional[_ClassMap],
                  module_locks: Dict[str, str]) -> Optional[str]:
    """Resolve a with-item context expr to a lock name, via the class
    attr map (``self._lock`` / condition attrs) or module globals."""
    if isinstance(expr, ast.Attribute) and \
            isinstance(expr.value, ast.Name) and expr.value.id == "self":
        if cm is not None:
            if expr.attr in cm.locks:
                return cm.locks[expr.attr]
            if expr.attr in cm.conds:
                return cm.conds[expr.attr]
        return None
    if isinstance(expr, ast.Name):
        return module_locks.get(expr.id)
    return None


def _walk_fn(fn: ast.FunctionDef, cm: Optional[_ClassMap],
             module_locks: Dict[str, str], path: str,
             edges: Dict[str, Set[str]],
             sites: Dict[Tuple[str, str], Tuple[str, int]],
             findings: List[Finding], *, expand: bool = True) -> None:
    """Intra-function held-stack walk; records edges/blocking findings."""

    def visit(node: ast.AST, held: List[str],
              loop_depth: int) -> None:
        if isinstance(node, ast.With):
            acquired: List[str] = []
            for item in node.items:
                name = _lock_name_of(item.context_expr, cm, module_locks)
                if name is not None:
                    for h in held:
                        if h != name:
                            edges.setdefault(h, set()).add(name)
                            sites.setdefault((h, name),
                                             (path, node.lineno))
                    acquired.append(name)
            for child in node.body:
                visit(child, held + acquired, loop_depth)
            return
        if isinstance(node, (ast.While, ast.For)):
            for child in ast.iter_child_nodes(node):
                visit(child, held, loop_depth + 1)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not fn:
            return  # nested defs run later, under unknown locks
        if isinstance(node, ast.Call) and held:
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr == "wait":
                recv_name = _lock_name_of(
                    f.value, cm, module_locks) if isinstance(
                        f, ast.Attribute) else None
                if recv_name is not None and loop_depth == 0:
                    findings.append(Finding(
                        "lock-wait-no-loop", path, node.lineno,
                        f"Condition.wait() on {recv_name!r} outside a "
                        f"while-predicate loop (spurious wakeups); use "
                        f"`while pred: cv.wait()` or wait_for"))
            elif attr in BLOCKING:
                if not _is_str_method(f):
                    findings.append(Finding(
                        "lock-blocking-call", path, node.lineno,
                        f"call to {attr}() while holding "
                        f"{held!r} — blocks every thread parked on "
                        f"the lock"))
            elif (expand and cm is not None
                  and isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "self" and attr in cm.methods):
                # one-level expansion: locks the callee acquires become
                # edges from everything currently held
                callee = cm.methods[attr]
                sub_edges: Dict[str, Set[str]] = {}
                _walk_fn(callee, cm, module_locks, path, sub_edges,
                         sites, [], expand=False)
                callee_locks: Set[str] = set(sub_edges)
                for tos in sub_edges.values():
                    callee_locks |= tos
                for node2 in ast.walk(callee):
                    if isinstance(node2, ast.With):
                        for item in node2.items:
                            nm = _lock_name_of(item.context_expr, cm,
                                               module_locks)
                            if nm is not None:
                                callee_locks.add(nm)
                for nm in callee_locks:
                    for h in held:
                        if h != nm:
                            edges.setdefault(h, set()).add(nm)
                            sites.setdefault((h, nm),
                                             (path, node.lineno))
        for child in ast.iter_child_nodes(node):
            visit(child, held, loop_depth)

    for stmt in fn.body:
        visit(stmt, [], 0)


def _is_str_method(f: ast.expr) -> bool:
    return (isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Constant)
            and isinstance(f.value.value, str))


def _sccs(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan SCCs of the name graph; only components of size > 1 (or
    explicit self-loops) are cycles."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(edges.get(v, ())):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1 or v in edges.get(v, ()):
                out.append(sorted(comp))

    nodes = set(edges)
    for tos in edges.values():
        nodes |= tos
    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return out


@register_pass("locks", RULES)
def lock_pass(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    edges: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int]] = {}

    for sf in corpus.under("reflow_tpu/"):
        if sf.tree is None or sf.path.startswith("reflow_tpu/analysis/"):
            continue
        # unnamed locks
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("Lock", "RLock") and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id == "threading":
                findings.append(Finding(
                    "lock-unnamed", sf.path, node.lineno,
                    f"threading.{node.func.attr}() — use "
                    f"named_lock(...) so both lock-order detectors "
                    f"can see it"))
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "Condition" and not node.args:
                findings.append(Finding(
                    "lock-unnamed", sf.path, node.lineno,
                    "bare threading.Condition() allocates a hidden "
                    "RLock — pass a named_lock()"))
        # module-level named locks
        module_locks: Dict[str, str] = {}
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.targets[0], ast.Name):
                call = _find_call(node.value, "named_lock")
                if call is not None and call.args:
                    nm = _literal_prefix(call.args[0])
                    if nm:
                        module_locks[node.targets[0].id] = nm
        # held-stack walk per function/method
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                cm = _scan_class(node, module_locks)
                for m in cm.methods.values():
                    _walk_fn(m, cm, module_locks, sf.path, edges,
                             sites, findings)
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                _walk_fn(node, None, module_locks, sf.path, edges,
                         sites, findings)

    for comp in _sccs(edges):
        where = []
        for a in comp:
            for b in comp:
                if b in edges.get(a, ()):
                    p, ln = sites[(a, b)]
                    where.append(f"{a}->{b} at {p}:{ln}")
        p, ln = sites[next((a, b) for a in comp for b in comp
                           if b in edges.get(a, ()))]
        findings.append(Finding(
            "lock-order-cycle", p, ln,
            f"held-before cycle over {comp}: " + "; ".join(where)))
    return findings
