"""Env-knob pass: every ``REFLOW_*`` read goes through the registry.

``reflow_tpu/utils/config.py`` is the single place a ``REFLOW_*``
environment variable may be read raw: it declares each knob (type,
default, one-line doc) and exposes typed accessors. Three rules keep
that true:

- **env-knob-direct** — ``os.environ.get("REFLOW_X")`` (or subscript)
  anywhere else. Direct reads fork the default value from the declared
  one and hide the knob from ``knob_table()`` / the docs.
- **env-knob-undeclared** — an accessor call (``env_flag("REFLOW_X")``
  …) naming a knob the registry does not declare. The accessors raise
  ``KeyError`` at runtime for these; the lint catches them before any
  code path runs.
- **env-knob-undocumented** — a declared knob whose name never appears
  in ``docs/guide.md``. The guide embeds ``knob_table()``'s rows, so a
  missing name means the table went stale.

Writes (``env["REFLOW_X"] = ...``, ``setdefault``) are exempt — the
bench harness builds child-process environments and that is the point.
"""

from __future__ import annotations

import ast
import os
from typing import List

from reflow_tpu.analysis.core import Corpus, Finding, register_pass

RULES = {
    "env-knob-direct": "REFLOW_* must be read via utils/config.py "
                       "accessors",
    "env-knob-undeclared": "accessor call names a knob declare() never "
                           "registered",
    "env-knob-undocumented": "declared knob missing from docs/guide.md",
}

_ACCESSORS = ("env_flag", "env_int", "env_float", "env_str")


def _first_str(arg: ast.expr) -> str:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    return ""


@register_pass("envknobs", RULES)
def envknob_pass(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    try:
        from reflow_tpu.utils.config import KNOBS
        declared = set(KNOBS)
    except Exception:  # registry import broken: other rules still run
        declared = None

    for sf in corpus.files.values():
        if sf.tree is None or sf.path.endswith("utils/config.py") \
                or sf.path.startswith("reflow_tpu/analysis/"):
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            attr = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if attr == "get" and isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Attribute) and \
                    f.value.attr == "environ" and node.args:
                name = _first_str(node.args[0])
                if name.startswith("REFLOW_"):
                    findings.append(Finding(
                        "env-knob-direct", sf.path, node.lineno,
                        f"direct os.environ read of {name!r} — use "
                        f"the utils/config.py accessor so the default "
                        f"and doc stay single-sourced"))
            elif attr in _ACCESSORS and node.args:
                name = _first_str(node.args[0])
                if name.startswith("REFLOW_") and declared is not None \
                        and name not in declared:
                    findings.append(Finding(
                        "env-knob-undeclared", sf.path, node.lineno,
                        f"{attr}({name!r}) but the registry never "
                        f"declare()d it — add it to "
                        f"reflow_tpu/utils/config.py"))
        # environ["REFLOW_X"] subscript READS (loads only)
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load) and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr == "environ":
                name = _first_str(node.slice)
                if name.startswith("REFLOW_"):
                    findings.append(Finding(
                        "env-knob-direct", sf.path, node.lineno,
                        f"direct os.environ[{name!r}] read — use the "
                        f"utils/config.py accessor"))

    if declared:
        guide = os.path.join(corpus.root, "docs", "guide.md")
        try:
            guide_text = open(guide, encoding="utf-8").read()
        except OSError:
            guide_text = ""
        for name in sorted(declared):
            if name not in guide_text:
                findings.append(Finding(
                    "env-knob-undocumented",
                    "reflow_tpu/utils/config.py", 1,
                    f"knob {name} is declared but never mentioned in "
                    f"docs/guide.md — regenerate the knob table "
                    f"(knob_table())"))
    return findings
