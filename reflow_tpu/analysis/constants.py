"""HLO constant audit, folded into reflow-lint as the opt-in slow pass.

The remote-device (tunnel) runtime degrades process-wide — every
subsequent dispatch pays ~88ms, permanently — after executing any
program whose HLO carries a constant with >= 2 elements (measured:
splat s32[4] poisons; scalar and 1-element constants do not). This
audit runs each benchmark workload at tiny scale on the CPU backend
with XLA HLO dumps enabled and reports every multi-element constant
per compiled program, so no such literal ever ships in a hot-path
program.

Unlike the AST passes this one actually *executes* the workloads
(several child processes, tens of seconds each), so it only runs under
``tools/reflow_lint.py --hlo``; findings come back through the same
``reflow.lint/1`` report under the ``hlo-multi-element-constant``
rule. ``tools/audit_constants.py`` remains as a thin shim over this
module.
"""

from __future__ import annotations

import glob
import os
import re
import shutil
import subprocess
import sys
from typing import List

from reflow_tpu.analysis.core import Finding, RULES

RULES.update({
    "hlo-multi-element-constant": "compiled programs must not embed "
                                  ">=2-element HLO constants (tunnel "
                                  "runtime poison); --hlo only",
})

WORKLOADS = ("pagerank", "tfidf", "knn", "image_embed",
             "sharded_pagerank", "minmax")

_CHILD = r'''
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, "@REPO@")
from reflow_tpu.executors import get_executor
from reflow_tpu.scheduler import DirtyScheduler

w = "@WORKLOAD@"
if w == "pagerank":
    from bench import _build_pagerank
    from reflow_tpu.workloads import pagerank
    pr, web = _build_pagerank(2_000, 20_000, 0.01, 1e-4)
    sched = DirtyScheduler(pr.graph, get_executor("tpu"))
    sched.push(pr.teleport, pagerank.teleport_batch(2_000))
    sched.push(pr.edges, web.initial_batch())
    sched.tick()
    sched.push(pr.edges, web.churn(0.01))
    sched.tick()
elif w == "sharded_pagerank":
    from reflow_tpu.parallel import make_mesh
    from reflow_tpu.parallel.shard import ShardedTpuExecutor
    from reflow_tpu.workloads import pagerank
    N, E = 2_048, 16_384
    pg = pagerank.build_graph(N, tol=1e-4, arena_capacity=1 << 18)
    web = pagerank.WebGraph.random(N, E, seed=11)
    sched = DirtyScheduler(pg.graph, ShardedTpuExecutor(make_mesh()))
    sched.push(pg.teleport, pagerank.teleport_batch(N))
    sched.push(pg.edges, web.initial_batch())
    sched.tick()
    sched.push(pg.edges, web.churn(0.01))
    sched.tick()
elif w == "tfidf":
    from reflow_tpu.workloads import tfidf
    n_pairs, n_terms, n_docs = 1 << 12, 1 << 10, 64
    corpus = tfidf.Corpus(n_pairs, n_terms)
    tg = tfidf.build_graph(n_pairs, n_terms, n_docs)
    sched = DirtyScheduler(tg.graph, get_executor("tpu"))
    rng = np.random.default_rng(1)
    words = np.array([f"t{i}" for i in range(500)])
    def text():
        return " ".join(rng.choice(words, size=rng.integers(20, 60)))
    from reflow_tpu.delta import DeltaBatch
    sched.push(tg.tokens, DeltaBatch.concat(
        [corpus.edit(d, text()) for d in range(8)]))
    sched.tick()
    for i in range(3):
        sched.push(tg.tokens, corpus.edit(i, text()))
        sched.tick()
elif w == "knn":
    from reflow_tpu.workloads import knn
    from reflow_tpu.delta import DeltaBatch
    Q, D, dim, k, chunk = 16, 4096, 32, 4, 1024
    kg = knn.build_graph(Q, D, dim, k, scan_chunk=chunk)
    store = knn.EmbeddingStore.create(dim, seed=3)
    sched = DirtyScheduler(kg.graph, get_executor("tpu"))
    qvecs = store._random(Q)
    sched.push(kg.queries, DeltaBatch(
        np.arange(Q, dtype=np.int64), qvecs, np.ones(Q, np.int64)))
    sched.push(kg.docs, store.insert_batch(np.arange(256)))
    sched.tick()
    sched.push(kg.docs, store.insert_batch(np.arange(256, 320)))
    sched.tick()
    sched.push(kg.docs, store.retract_batch(np.arange(8)))
    sched.tick()
elif w == "minmax":
    from reflow_tpu.delta import DeltaBatch, Spec
    from reflow_tpu.graph import FlowGraph
    g = FlowGraph("mm")
    spec = Spec((), np.float32, key_space=64)
    s = g.source("s", spec)
    g.sink(g.reduce(s, "min", name="lo", candidates=8), "out")
    sched = DirtyScheduler(g, get_executor("tpu"))
    rng = np.random.default_rng(2)
    rows = [(int(rng.integers(0, 64)), float(rng.integers(0, 9)), 1)
            for _ in range(80)]
    def push(rs):
        sched.push(s, DeltaBatch(np.array([r[0] for r in rs]),
                                 np.array([r[1] for r in rs], np.float32),
                                 np.array([r[2] for r in rs])))
        sched.tick()
    push(rows)
    push([(k, v, -w) for k, v, w in rows[:20]])
elif w == "image_embed":
    from reflow_tpu.models import VIT_TINY, init_vit
    from reflow_tpu.workloads import image_embed
    params = init_vit(0, **VIT_TINY)
    params["_cfg"] = VIT_TINY
    ig = image_embed.build_graph(256, 4, params)
    sched = DirtyScheduler(ig.graph, get_executor("tpu"))
    stream = image_embed.ImageStream(params, seed=5)
    ids = np.arange(8)
    sched.push(ig.images, stream.insert(ids, ids % 4))
    sched.tick()
    ids2 = np.arange(8, 16)
    sched.push(ig.images, stream.insert(ids2, ids2 % 4))
    sched.tick()
print("CHILD_OK")
'''

PAT = re.compile(r"=\s*([a-z0-9]+)\[([\d,]+)\]\S*\s+constant\(")


def audit(workload: str, repo: str) -> list:
    """(module, shape, line) for every multi-element constant the
    workload's compiled programs embed; a single ("CHILD_FAILED", …)
    entry when the child process itself died."""
    dump = f"/tmp/const_audit_{workload}"
    shutil.rmtree(dump, ignore_errors=True)
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_dump_to={dump} --xla_dump_hlo_as_text"
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    if workload == "sharded_pagerank":
        env["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
    child = _CHILD.replace("@REPO@", repo).replace("@WORKLOAD@", workload)
    r = subprocess.run([sys.executable, "-c", child],
                       capture_output=True, text=True, env=env,
                       timeout=900)
    if "CHILD_OK" not in r.stdout:
        return [("CHILD_FAILED", r.stderr.strip().splitlines()[-3:])]
    bad = []
    for f in sorted(glob.glob(f"{dump}/*before_optimizations*.txt")):
        mod = os.path.basename(f).split(".")[1]
        for line in open(f):
            m = PAT.search(line)
            if not m:
                continue
            dims = [int(d) for d in m.group(2).split(",")]
            n = 1
            for d in dims:
                n *= d
            if n >= 2:
                bad.append((mod, f"{m.group(1)}{dims}",
                            line.strip()[:100]))
    return bad


def hlo_pass(repo: str, workloads=None) -> List[Finding]:
    """The slow pass: one Finding per multi-element constant. Not
    registered with the fast-pass registry — the CLI invokes it only
    under ``--hlo``."""
    findings: List[Finding] = []
    for w in (workloads or WORKLOADS):
        for item in audit(w, repo):
            findings.append(Finding(
                "hlo-multi-element-constant", f"<workload:{w}>", 0,
                "  ".join(str(x) for x in item)))
    return findings
