"""Seam-hygiene pass: CrashInjector seam strings.

Every crash seam in the tree — a literal passed to ``_crash_point``,
``_chaos_point`` (the process harness's kill/respawn seams, scoped
``proc_*@<node>``) or ``CrashInjector.point`` — is a
differential-testing contract: recovery tests arm
``CrashInjector(at=N, only=<seam>)`` and assert the exactly-once
invariants around that exact cut. Two rules keep the contract honest:

- **seam-grammar** — the seam name must be ``lower_snake`` and, when a
  graph scope is attached, follow ``<seam>@<graph>``. Call sites that
  build the scope dynamically (``f"pool_window@{picked.name}"`` or the
  frontend's ``f"{name}@{self.name}"`` helper) are checked on their
  literal part: the seam prefix must end exactly at the ``@``.
- **seam-untested** — a seam no test file ever mentions is dead
  differential coverage: a crash cut nobody asserts on. The reference
  check is substring-based over ``tests/`` (a test arming
  ``"pump_before_tick@wal"`` covers the ``pump_before_tick`` seam).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from reflow_tpu.analysis.core import Corpus, Finding, register_pass

_SEAM_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_SCOPED_RE = re.compile(r"^[a-z][a-z0-9_]*@[A-Za-z0-9_.-]+$")

RULES = {
    "seam-grammar": "crash seams must match <seam> or <seam>@<graph>",
    "seam-untested": "every crash seam needs >=1 test referencing it",
}


def _seam_literals(tree: ast.AST) -> List[Tuple[str, int, bool]]:
    """(seam_text, line, is_partial) for every seam-emitting call.
    ``is_partial`` marks f-strings whose graph part is dynamic — only
    the literal prefix is returned."""
    out: List[Tuple[str, int, bool]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        attr = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if attr not in ("_crash_point", "_chaos_point", "point"):
            continue
        if attr == "point":
            # only CrashInjector-ish receivers: self._crash.point(...)
            if not (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Attribute)
                    and "crash" in f.value.attr):
                continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno, False))
        elif isinstance(arg, ast.JoinedStr):
            head = ""
            for part in arg.values:
                if isinstance(part, ast.Constant):
                    head += str(part.value)
                else:
                    break
            out.append((head, node.lineno, True))
    return out


@register_pass("seams", RULES)
def seam_pass(corpus: Corpus) -> List[Finding]:
    findings: List[Finding] = []
    tests_text = "\n".join(sf.text for sf in corpus.under("tests/"))
    seen: Dict[str, Tuple[str, int]] = {}

    for sf in corpus.under("reflow_tpu/"):
        if sf.tree is None or sf.path.startswith("reflow_tpu/analysis/"):
            continue
        for seam, line, partial in _seam_literals(sf.tree):
            if partial:
                # dynamic graph scope: literal prefix must be
                # "<seam>@" (or empty — the scoping helper re-emitting
                # its argument, which was checked at ITS call sites)
                if seam and not (seam.endswith("@")
                                 and _SEAM_RE.match(seam[:-1])):
                    findings.append(Finding(
                        "seam-grammar", sf.path, line,
                        f"dynamic seam prefix {seam!r} must be "
                        f"'<seam>@' (lower_snake seam, then the "
                        f"graph scope)"))
                    continue
                base = seam[:-1] if seam else None
            else:
                if not (_SEAM_RE.match(seam) or _SCOPED_RE.match(seam)):
                    findings.append(Finding(
                        "seam-grammar", sf.path, line,
                        f"seam {seam!r} does not match <seam> or "
                        f"<seam>@<graph> (lower_snake)"))
                    continue
                base = seam.split("@", 1)[0]
            if base:
                seen.setdefault(base, (sf.path, line))

    for base in sorted(seen):
        if base not in tests_text:
            path, line = seen[base]
            findings.append(Finding(
                "seam-untested", path, line,
                f"crash seam {base!r} has no test referencing it — "
                f"arm CrashInjector(only={base!r}...) somewhere in "
                f"tests/ and assert the recovery invariant"))
    return findings
