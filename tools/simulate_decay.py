"""Offline frontier-decay model of the PageRank churn tick (numpy).

Reproduces the delta-vector loop's per-pass dynamics (tol-gated emission
diff over the bench graph at full scale) on the host, to size the budget
tiers against the REAL frontier: per pass it reports live frontier keys,
frontier edges, which gather tier the device loop would pick, and the
modeled gather/scatter row cost. This is the tool that says whether the
measured per-pass wall is physics (frontier edges / scatter rate) or
waste (tier misfit / dense fallback).

Run: python tools/simulate_decay.py   (pure numpy, ~20s)
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    from bench import _build_pagerank
    from reflow_tpu.executors.linear_fixpoint import _edge_budget_tiers

    n_nodes, n_edges, churn, tol = 100_000, 1_000_000, 0.01, 1e-4
    damping = 0.85
    pr, web = _build_pagerank(n_nodes, n_edges, churn, tol)
    arena_cap = pr.join.op.arena_capacity
    tiers = _edge_budget_tiers(arena_cap)
    print(f"arena {arena_cap}, tiers {tiers}")

    src, dst = web.src.copy(), web.dst.copy()
    deg = np.zeros(n_nodes, np.int64)
    np.add.at(deg, src, 1)

    def converge(r, emitted, src, dst, deg, trace=False):
        inv = np.where(deg > 0, 1.0 / np.maximum(deg, 1), 0.0)
        rows = []
        for it in range(200):
            contrib = np.zeros(n_nodes)
            np.add.at(contrib, dst, r[src] * inv[src])
            agg = (1.0 - damping) + damping * contrib
            changed = np.abs(agg - emitted) > tol
            if not changed.any():
                break
            emitted = np.where(changed, agg, emitted)
            r = emitted
            if trace:
                fkeys = changed & (deg > 0)
                fedges = int(deg[fkeys].sum())
                rows.append((int(changed.sum()), fedges))
        return emitted, rows

    # base convergence (phase-A analog of the initial build)
    emitted = np.zeros(n_nodes)
    emitted, _ = converge(np.ones(n_nodes), emitted, src, dst, deg)

    # one churn tick, matching WebGraph.churn exactly: rewire the DST of
    # 1% of edges (out-degree preserving — src and deg are untouched)
    rng = np.random.default_rng(99)
    ix = rng.choice(n_edges, max(1, int(churn * n_edges)), replace=False)
    dst[ix] = rng.integers(0, n_nodes, len(ix))
    _, rows = converge(emitted, emitted.copy(), src, dst, deg, trace=True)

    gs_rate = 74e6   # scatter/gather rows per second (measured, VPU)
    dense_rows = 3 * arena_cap          # gather + push + scatter full arena
    total_ms = 0.0
    total_edges = 0
    print(f"{'pass':>4} {'fkeys':>8} {'fedges':>9} {'tier':>8} "
          f"{'rows':>9} {'ms':>6}")
    for i, (fk, fe) in enumerate(rows):
        fit = [t for t in tiers if t >= fe]
        tier = min(fit) if fit else 0
        rows_proc = 3 * tier if tier else dense_rows
        ms = rows_proc / gs_rate * 1e3
        total_ms += ms
        total_edges += fe
        print(f"{i:>4} {fk:>8} {fe:>9} {tier or 'dense':>8} "
              f"{rows_proc:>9} {ms:>6.1f}")
    ideal_ms = 3 * total_edges / gs_rate * 1e3
    print(f"passes {len(rows)}, frontier edges {total_edges}")
    print(f"modeled loop {total_ms:.0f} ms; perfect-fit floor "
          f"{ideal_ms:.0f} ms")


if __name__ == "__main__":
    main()
