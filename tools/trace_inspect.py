#!/usr/bin/env python3
"""Per-stage latency breakdown + critical path of a reflow trace.

Usage::

    python tools/trace_inspect.py trace.json           # human report
    python tools/trace_inspect.py trace.json --json    # machine summary

Input is the Chrome trace-event JSON written by
``reflow_tpu.obs.export_chrome_trace()`` (either the
``{"traceEvents": [...]}`` object or a bare event array). The report
has two halves:

- **spans**: p50/p99/total for every named span across all tracks
  (windows, ticks, WAL appends/fsyncs, device dispatches), plus the
  **durability pipeline** split: ``wal_fsync`` spans on the
  ``wal-committer`` track ran off the dispatch path (the asynchronous
  committer), spans on the ``wal`` track ran on it (inline barriers) —
  ``offpath_fsync_frac`` is the share of fsync time the pipeline moved
  off the pump, ``fsync_covered_mean`` the group-commit fan-in;
- **per-device**: ``device_dispatch`` busy time grouped by the executing
  device (from the placement/sharding tags on dispatch spans) — the
  placement-skew view of a spread-placed serving tier;
- **tickets**: the sampled tickets' end-to-end latency decomposed into
  the six pipeline stages (admission → coalesce → sched_delay →
  execute → fsync → resolve), with the **critical path** — stages
  ranked by their mean share of end-to-end latency — and the worst
  decomposition deviation (stage sums are tiled, so this should sit at
  ~0%; large values mean a clock or export bug).

A trace recorded under WAL shipping (``wal/ship.py`` +
``serve/replica.py``) carries ``ship_segment`` spans on the
``wal-shipper`` track and ``replica_replay`` spans on per-replica
tracks; the report folds them into a **replication** section — per
follower byte flow and NACKs, per replica applied records, replay time,
and the published-horizon lag after each window.

A trace whose spans carry **causality tokens**
(``obs.trace.mint_cause`` stamped onto writes, shipments, and delta
frames while tracing is on) gets a **causal chains** section: spans
sharing a token in ``args.cause`` / ``args.causes`` are stitched into
cross-process chains, and tokens co-occurring on one span (a chunk's
own token beside the write tokens it carries) are bridged into one
group — so a write's journey ``producer_submit`` → ``rpc_admit`` →
``admission`` → ``wal_append`` → ``ship_segment`` → ``net_send`` →
``replica_replay`` → ``sub_fanout`` → ``sub_deliver`` reads as a
single chain even though no single process saw it whole. Groups
carrying all nine links are **full chains** and feed the **freshness**
section: ack→delta-visible latency tiled into admission / durability /
ship / apply / fanout / deliver, with the worst tiling deviation.
Passing several trace files merges them onto one timeline via their
``baseTimeS`` anchors (same-host processes share the monotonic
clock). ``--require-chain a,b,c`` makes the exit status assert that
at least one causal group carries all the named spans (the fleet
bench's smoke check).

A trace recorded across a **leader failover** (``serve/failover.py``)
carries ``failover_elect`` / ``failover_replay`` spans on the
``failover`` and replica tracks and ``fence_reject`` spans wherever a
zombie write was turned away; the report folds them into a **failover**
section — promotions, elect/replay time, and fence rejects by kind
(append vs shipment) — the promotion timeline an operator reads after
pulling a leader.

A trace recorded under a live ``ControlPlane`` also carries its
actuations as zero-duration ``control.<action>`` spans on the
``control`` track; the report surfaces them as **control actions** —
counts per action (brownout steps/recoveries, respawns, breaker
opens/probes/closes, scale events, floor reclaims) — so an operator can
line the controller's interventions up against the data-path spans they
reacted to.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reflow_tpu.obs.export import ticket_timelines  # noqa: E402
from reflow_tpu.obs.trace import STAGES  # noqa: E402
from reflow_tpu.utils.metrics import percentile  # noqa: E402

#: the canonical follow-the-write chain, producer keystroke to
#: subscriber-visible answer; a causal group carrying all nine links
#: is a *full chain* and feeds the freshness decomposition
FULL_CHAIN = ("producer_submit", "rpc_admit", "admission", "wal_append",
              "ship_segment", "net_send", "replica_replay",
              "sub_fanout", "sub_deliver")

#: ack→push freshness stages; each tiles between two chain boundaries
FRESHNESS_STAGES = ("admission", "durability", "ship", "apply",
                    "fanout", "deliver")

#: span kinds ONE write's token must itself carry for its ack→deliver
#: freshness decomposition (the cut points in ``_chain_freshness``).
#: Deliberately narrower than FULL_CHAIN: ``net_send`` joins a write's
#: group only through the shipped chunk's own token, and a bridged
#: group can blob MANY writes together — decomposing over group bounds
#: would mix cut points from different writes and break the tiling.
FRESHNESS_SPANS = ("producer_submit", "rpc_admit", "wal_append",
                   "replica_replay", "sub_fanout", "sub_deliver")


def load_events(path: str) -> list:
    with open(path) as f:
        raw = json.load(f)
    return raw["traceEvents"] if isinstance(raw, dict) else raw


def load_traces(paths) -> tuple:
    """Load + merge one or more trace files onto a shared timeline.

    Every ``export_chrome_trace`` file carries ``baseTimeS`` — the
    ``perf_counter()`` instant its ``ts=0`` maps to. Processes on one
    host share that clock, so shifting each file's events by
    ``(baseTimeS - min(baseTimeS)) * 1e6`` puts all spans on directly
    comparable microseconds. Per-file ``tid`` namespaces are kept
    disjoint by rewriting tids to ``(file_index, tid)`` pairs. Returns
    ``(events, files)`` where ``files`` records each path's base and
    node id."""
    loaded, files = [], []
    for i, path in enumerate(paths):
        with open(path) as f:
            raw = json.load(f)
        if isinstance(raw, dict):
            events = raw.get("traceEvents", [])
            base = float(raw.get("baseTimeS") or 0.0)
            node = raw.get("node")
        else:
            events, base, node = raw, 0.0, None
        files.append({"path": path, "base_time_s": base, "node": node})
        loaded.append((i, events, base))
    base0 = min((f["base_time_s"] for f in files), default=0.0)
    merged = []
    for i, events, base in loaded:
        off_us = (base - base0) * 1e6
        for ev in events:
            e = dict(ev)
            if "tid" in e:
                e["tid"] = (i, e["tid"])
            if e.get("ph") == "X":
                e["ts"] = float(e.get("ts", 0.0)) + off_us
            merged.append(e)
    return merged, files


def read_report(obj: dict) -> dict:
    """Normalize a ``--json`` report across schema versions: a
    ``reflow.trace_inspect/1`` report (single file, token-keyed chains,
    no freshness section) reads back with the /2 keys defaulted, so
    downstream consumers can be written once against /2."""
    out = dict(obj)
    out.setdefault("schema", "reflow.trace_inspect/1")
    out.setdefault("freshness", None)
    if not out.get("trace_files"):
        tf = out.get("trace_file")
        out["trace_files"] = [tf] if tf else []
    ca = out.get("causal")
    if ca is not None:
        ca.setdefault("groups", ca.get("chains", 0))
        ca.setdefault("full_chains", 0)
    return out


def _chain_freshness(bounds) -> tuple:
    """One full chain's ack→deliver decomposition from its per-span
    time bounds: ``(stage_durs_us, e2e_us, deviation_frac)``. The six
    stages tile the boundaries producer_submit.start → first
    rpc_admit.end (a lost ack's dedup re-admit lands later) →
    wal_append.end → replica_replay.start → min(replica_replay.end,
    sub_fanout.end) → sub_fanout.end → sub_deliver.end; a stage going
    negative (clock
    skew between merged files) is clamped to 0 and shows up in the
    deviation instead of silently corrupting a neighbor."""
    def _first_end(b):
        # min end when tracked (3-element bounds); a 2-element bound
        # (older report data, hand-built tests) falls back to max end
        return b[2] if len(b) > 2 else b[1]

    # the hub fans out synchronously inside the replay batch's span
    # (the window-retire callback), so replica_replay can CLOSE after
    # the push — even after the subscriber recorded delivery. The
    # first completed push therefore bounds apply completion from
    # above; taking the min keeps the cut sequence monotone instead
    # of charging the replay span's trailing bookkeeping to a
    # negative fanout stage.
    apply_done = min(bounds["replica_replay"][1],
                     bounds["sub_fanout"][1])
    cuts = (bounds["producer_submit"][0],
            _first_end(bounds["rpc_admit"]),
            bounds["wal_append"][1],
            bounds["replica_replay"][0],
            apply_done,
            bounds["sub_fanout"][1],
            bounds["sub_deliver"][1])
    raw = {name: cuts[i + 1] - cuts[i]
           for i, name in enumerate(FRESHNESS_STAGES)}
    stages = {name: max(0.0, v) for name, v in raw.items()}
    e2e = cuts[-1] - cuts[0]
    dev = (abs(sum(stages.values()) - e2e) / e2e) if e2e > 0 else 0.0
    return stages, e2e, dev, raw


def _freshness_summary(full_chains):
    """Aggregate the per-write decompositions of every ``(token, chain)``
    whose chain carries all of ``FRESHNESS_SPANS``; None when no write's
    chain is complete enough to decompose. ``worst`` names the chain
    with the largest tiling deviation and its unclamped stage deltas —
    a negative raw delta fingers the cut whose ordering broke."""
    if not full_chains:
        return None
    per_stage: dict = {s: [] for s in FRESHNESS_STAGES}
    e2e_list, devs = [], []
    worst = None
    for tok, ch in full_chains:
        stages, e2e, dev, raw = _chain_freshness(ch["bounds"])
        for s, v in stages.items():
            per_stage[s].append(v)
        e2e_list.append(e2e)
        devs.append(dev)
        if worst is None or dev > worst["dev_frac"]:
            worst = {"token": tok, "e2e_us": round(e2e, 3),
                     "dev_frac": round(dev, 6),
                     "raw_stage_us": {s: round(v, 3)
                                      for s, v in raw.items()}}
    mean_e2e = sum(e2e_list) / len(e2e_list)
    out_stages = {}
    for s in FRESHNESS_STAGES:
        vals = per_stage[s]
        mean = sum(vals) / len(vals)
        out_stages[s] = {
            "p50_us": round(percentile(vals, 50), 3),
            "p99_us": round(percentile(vals, 99), 3),
            "mean_share": (round(mean / mean_e2e, 4)
                           if mean_e2e else 0.0)}
    return {"chains": len(full_chains),
            "stages": out_stages,
            "e2e_p50_us": round(percentile(e2e_list, 50), 3),
            "e2e_p99_us": round(percentile(e2e_list, 99), 3),
            "max_dev_frac": round(max(devs), 6),
            "worst": worst}


def inspect(path, require_chain=None) -> dict:
    """Summarize one trace file (or a list of them, merged onto a
    shared timeline via ``baseTimeS``); the dict is the ``--json``
    output. ``require_chain`` (a list of span names) additionally
    reports, as ``causal.required_chains``, how many causal groups
    carry *all* of the named spans — the assertable form of "the
    end-to-end path survived"."""
    paths = [path] if isinstance(path, str) else list(path)
    events, files = load_traces(paths)
    by_name: dict = defaultdict(list)
    tracks = set()
    # numeric tid -> track name, from the thread_name metadata events
    tid_names = {ev.get("tid"): ev["args"]["name"] for ev in events
                 if ev.get("ph") == "M"
                 and ev.get("name") == "thread_name"}
    fsync_on, fsync_off, covered = [], [], []
    # executing-device busy time, from the device tag placement/sharding
    # stamps onto dispatch-side spans ("(default)" = untagged executor)
    dev_busy: dict = defaultdict(float)
    dev_dispatches: dict = defaultdict(int)
    # pipelined staging: window_stage spans carry args.inflight (windows
    # already dispatched when this stage began) — inflight > 0 means the
    # host staging wall overlapped device compute
    stage_total, stage_overlapped = 0.0, 0.0
    # pump_execute spans carry args.depth (in-flight windows INCLUDING
    # the one being dispatched) — the occupancy histogram of the pipeline
    depth_counts: dict = defaultdict(int)
    # WAL shipping / replica replay (wal/ship.py, serve/replica.py):
    # ship_segment spans carry the per-follower byte flow, replica_replay
    # spans the applied windows and the lag the replica published after
    # each one — together the replica-lag breakdown
    ship_by_follower: dict = defaultdict(
        lambda: {"shipments": 0, "bytes": 0, "nacks": 0, "ship_ms": 0.0})
    replay_by_replica: dict = defaultdict(
        lambda: {"shipments": 0, "records_applied": 0, "replay_ms": 0.0,
                 "horizon": 0, "lag_ticks": 0, "max_lag_ticks": 0})
    # tiled maintenance (wal/compact.py, utils/checkpoint.py,
    # wal/ship.py): compact_tile per folded key-range tile (resident
    # fold bytes), ckpt_tile per checkpoint tile frame (full/delta),
    # tile_ship per checkpoint file shipped as a CRC-framed unit —
    # together the bounded-peak-memory evidence for a tiled pass
    tiles_acc = {
        "compact_tile": {"tiles": 0, "ms": 0.0, "parts": 0,
                         "max_resident_bytes": 0},
        "ckpt_tile": {"tiles": 0, "ms": 0.0, "full": 0, "delta": 0,
                      "max_bytes": 0},
        "tile_ship": {"units": 0, "ms": 0.0, "bytes": 0,
                      "retries": 0, "rejects": 0},
    }
    # failover (serve/failover.py, serve/replica.py, wal/log.py):
    # failover_elect marks the decision, failover_replay the winner's
    # mirrored-prefix replay, fence_reject every zombie write the new
    # epoch turned away — the promotion timeline, span by span
    failover_events: list = []
    fence_rejects: dict = defaultdict(int)
    # wire transport (net/client.py): net_send per roundtrip on the
    # net/<follower> track, net_reconnect per recovery attempt — the
    # per-link health breakdown
    net_by_link: dict = defaultdict(
        lambda: {"sends": 0, "send_failures": 0, "send_ms": 0.0,
                 "ops": defaultdict(int), "reconnect_attempts": 0,
                 "reconnects": 0, "reconnect_ms": 0.0,
                 "last_state": None})
    # causal chains (obs.trace.mint_cause): spans sharing one
    # args.cause token are one write's cross-process journey —
    # chains[token] = {span name -> [durs]}, per-name time bounds, and
    # the chain's overall span. A span may carry several tokens (one
    # args.cause plus an args.causes list — e.g. a shipped chunk's own
    # token alongside the write tokens it carries); tokens co-occurring
    # on one span are bridged into a single *group* (union-find), which
    # is how a write token meets the chunk token its bytes rode in
    # net_send.
    chains: dict = defaultdict(
        lambda: {"links": defaultdict(list), "bounds": {},
                 "t0": None, "t1": None})
    uf_parent: dict = {}

    def _find(t):
        r = t
        while uf_parent.setdefault(r, r) != r:
            r = uf_parent[r]
        while uf_parent[t] != r:
            uf_parent[t], t = r, uf_parent[t]
        return r

    def _union(a, b):
        ra, rb = _find(a), _find(b)
        if ra != rb:
            uf_parent[rb] = ra
    for ev in events:
        if ev.get("ph") == "X":
            by_name[ev.get("name", "?")].append(float(ev.get("dur", 0.0)))
            tracks.add(ev.get("tid"))
            a = ev.get("args") or {}
            tokens = []
            if a.get("cause"):
                tokens.append(a["cause"])
            for tok in a.get("causes") or ():
                if tok not in tokens:
                    tokens.append(tok)
            if tokens:
                ts = float(ev.get("ts", 0.0))
                dur = float(ev.get("dur", 0.0))
                name = ev.get("name", "?")
                for tok in tokens:
                    _find(tok)
                    ch = chains[tok]
                    ch["links"][name].append(dur)
                    b = ch["bounds"].get(name)
                    if b is None:
                        # [min start, max end, min end]: a resubmitted
                        # write can carry several same-name spans (the
                        # dedup re-admit after an ack was lost); cuts
                        # that mean "first time this happened" read
                        # the min end
                        ch["bounds"][name] = [ts, ts + dur, ts + dur]
                    else:
                        b[0] = min(b[0], ts)
                        b[1] = max(b[1], ts + dur)
                        b[2] = min(b[2], ts + dur)
                    ch["t0"] = ts if ch["t0"] is None \
                        else min(ch["t0"], ts)
                    ch["t1"] = (ts + dur if ch["t1"] is None
                                else max(ch["t1"], ts + dur))
                for tok in tokens[1:]:
                    _union(tokens[0], tok)
            if ev.get("name") == "device_dispatch":
                dev = (ev.get("args") or {}).get("device") or "(default)"
                dev_busy[dev] += float(ev.get("dur", 0.0))
                dev_dispatches[dev] += 1
            if ev.get("name") == "window_stage":
                dur = float(ev.get("dur", 0.0))
                stage_total += dur
                if int((ev.get("args") or {}).get("inflight", 0) or 0) > 0:
                    stage_overlapped += dur
            if ev.get("name") == "pump_execute":
                d = (ev.get("args") or {}).get("depth")
                if d is not None:
                    depth_counts[int(d)] += 1
            if ev.get("name") == "ship_segment":
                a = ev.get("args") or {}
                st = ship_by_follower[a.get("follower") or "?"]
                st["shipments"] += 1
                st["bytes"] += int(a.get("bytes", 0) or 0)
                st["ship_ms"] += float(ev.get("dur", 0.0)) / 1e3
                if not a.get("ack", True):
                    st["nacks"] += 1
            if ev.get("name") == "replica_replay":
                a = ev.get("args") or {}
                track = tid_names.get(ev.get("tid"), "replica/?")
                name = track.split("/", 1)[1] if "/" in track else track
                st = replay_by_replica[name]
                st["shipments"] += 1
                st["records_applied"] += int(a.get("applied", 0) or 0)
                st["replay_ms"] += float(ev.get("dur", 0.0)) / 1e3
                st["horizon"] = max(st["horizon"],
                                    int(a.get("horizon", 0) or 0))
                lag = int(a.get("lag_ticks", 0) or 0)
                st["lag_ticks"] = lag
                st["max_lag_ticks"] = max(st["max_lag_ticks"], lag)
            if ev.get("name") == "compact_tile":
                a = ev.get("args") or {}
                st = tiles_acc["compact_tile"]
                st["tiles"] += 1
                st["ms"] += float(ev.get("dur", 0.0)) / 1e3
                st["parts"] += int(a.get("parts", 0) or 0)
                st["max_resident_bytes"] = max(
                    st["max_resident_bytes"],
                    int(a.get("resident_bytes", 0) or 0))
            if ev.get("name") == "ckpt_tile":
                a = ev.get("args") or {}
                st = tiles_acc["ckpt_tile"]
                st["tiles"] += 1
                st["ms"] += float(ev.get("dur", 0.0)) / 1e3
                kind = a.get("kind")
                if kind in ("full", "delta"):
                    st[kind] += 1
                st["max_bytes"] = max(st["max_bytes"],
                                      int(a.get("bytes", 0) or 0))
            if ev.get("name") == "tile_ship":
                a = ev.get("args") or {}
                st = tiles_acc["tile_ship"]
                st["ms"] += float(ev.get("dur", 0.0)) / 1e3
                if a.get("ok", True):
                    st["units"] += 1
                    st["bytes"] += int(a.get("bytes", 0) or 0)
                else:
                    st["rejects"] += 1
                if int(a.get("attempt", 0) or 0) > 0:
                    st["retries"] += 1
            if ev.get("name") == "failover_elect":
                a = ev.get("args") or {}
                failover_events.append({
                    "event": "elect", "winner": a.get("winner"),
                    "epoch": a.get("epoch"), "reason": a.get("reason"),
                    "drained_bytes": a.get("drained_bytes"),
                    "ms": round(float(ev.get("dur", 0.0)) / 1e3, 3)})
            if ev.get("name") == "failover_replay":
                a = ev.get("args") or {}
                failover_events.append({
                    "event": "replay", "epoch": a.get("epoch"),
                    "horizon": a.get("horizon"),
                    "replayed_pushes": a.get("replayed_pushes"),
                    "replayed_ticks": a.get("replayed_ticks"),
                    "ms": round(float(ev.get("dur", 0.0)) / 1e3, 3)})
            if ev.get("name") == "fence_reject":
                kind = (ev.get("args") or {}).get("kind") or "?"
                fence_rejects[kind] += 1
            if ev.get("name") in ("net_send", "net_reconnect"):
                a = ev.get("args") or {}
                track = tid_names.get(ev.get("tid"), "net/?")
                link = track.split("/", 1)[1] if "/" in track else track
                st = net_by_link[link]
                if ev["name"] == "net_send":
                    st["sends"] += 1
                    st["send_ms"] += float(ev.get("dur", 0.0)) / 1e3
                    st["ops"][a.get("op") or "?"] += 1
                    if not a.get("ok", True):
                        st["send_failures"] += 1
                else:
                    st["reconnect_attempts"] += 1
                    st["reconnect_ms"] += float(ev.get("dur", 0.0)) / 1e3
                    if a.get("ok") and a.get("recovered"):
                        st["reconnects"] += 1
                if a.get("state"):
                    st["last_state"] = a["state"]
                elif a.get("ok"):
                    st["last_state"] = "healthy"
            if ev.get("name") == "wal_fsync":
                dur = float(ev.get("dur", 0.0))
                if tid_names.get(ev.get("tid")) == "wal-committer":
                    fsync_off.append(dur)
                    covered.append(
                        float((ev.get("args") or {}).get("covered", 0)))
                else:
                    fsync_on.append(dur)
    spans = {
        name: {"count": len(durs),
               "p50_us": round(percentile(durs, 50), 3),
               "p99_us": round(percentile(durs, 99), 3),
               "total_ms": round(sum(durs) / 1e3, 3)}
        for name, durs in sorted(by_name.items())}

    tickets = ticket_timelines(events)
    e2e = [t["e2e_us"] for t in tickets.values()]
    stage_durs = {s: [t["stages"].get(s, 0.0) for t in tickets.values()]
                  for s in STAGES}
    mean_e2e = sum(e2e) / len(e2e) if e2e else 0.0
    stage_summary = {}
    for s in STAGES:
        durs = stage_durs[s]
        mean = sum(durs) / len(durs) if durs else 0.0
        stage_summary[s] = {
            "p50_us": round(percentile(durs, 50), 3),
            "p99_us": round(percentile(durs, 99), 3),
            "mean_share": round(mean / mean_e2e, 4) if mean_e2e else 0.0,
        }
    critical_path = sorted(
        STAGES, key=lambda s: stage_summary[s]["mean_share"],
        reverse=True)
    max_dev = 0.0
    for t in tickets.values():
        if t["e2e_us"] > 0:
            max_dev = max(max_dev, abs(t["sum_us"] - t["e2e_us"])
                          / t["e2e_us"])
    control_actions = {
        name[len("control."):]: len(durs)
        for name, durs in sorted(by_name.items())
        if name.startswith("control.")}
    # mega-tick occupancy: how much of the commit-window wall was the
    # device dispatch itself — the compiled-window path drives this
    # toward 1.0 (dispatch-bound), the per-tick crank leaves it low
    dispatch_us = sum(by_name.get("device_dispatch", ()))
    window_us = (sum(by_name.get("window", ()))
                 or sum(by_name.get("tick_many", ())))
    window_dispatch_frac = (round(dispatch_us / window_us, 4)
                            if window_us else 0.0)
    fsync_total = sum(fsync_on) + sum(fsync_off)
    durability = {
        "onpath_fsyncs": len(fsync_on),
        "offpath_fsyncs": len(fsync_off),
        "onpath_fsync_ms": round(sum(fsync_on) / 1e3, 3),
        "offpath_fsync_ms": round(sum(fsync_off) / 1e3, 3),
        "offpath_fsync_frac": (round(sum(fsync_off) / fsync_total, 4)
                               if fsync_total else 0.0),
        "fsync_covered_mean": (round(sum(covered) / len(covered), 2)
                               if covered else 0.0),
    }
    dev_total = sum(dev_busy.values())
    per_device = {
        dev: {"dispatches": dev_dispatches[dev],
              "busy_ms": round(busy / 1e3, 3),
              "share": round(busy / dev_total, 4) if dev_total else 0.0}
        for dev, busy in sorted(dev_busy.items())}
    stage_overlap_frac = (round(stage_overlapped / stage_total, 4)
                          if stage_total else 0.0)
    dispatch_by_depth = {str(d): n for d, n in sorted(depth_counts.items())}
    replication = None
    if ship_by_follower or replay_by_replica:
        for st in ship_by_follower.values():
            st["ship_ms"] = round(st["ship_ms"], 3)
        for st in replay_by_replica.values():
            st["replay_ms"] = round(st["replay_ms"], 3)
        replication = {
            "ship": {k: dict(v)
                     for k, v in sorted(ship_by_follower.items())},
            "replicas": {k: dict(v)
                         for k, v in sorted(replay_by_replica.items())},
            "max_lag_ticks": max(
                (v["max_lag_ticks"] for v in replay_by_replica.values()),
                default=0),
            "final_lag_ticks": max(
                (v["lag_ticks"] for v in replay_by_replica.values()),
                default=0),
        }
    network = None
    if net_by_link:
        network = {}
        for link, st in sorted(net_by_link.items()):
            network[link] = {
                "sends": st["sends"],
                "send_failures": st["send_failures"],
                "send_ms": round(st["send_ms"], 3),
                "ops": dict(sorted(st["ops"].items())),
                "reconnect_attempts": st["reconnect_attempts"],
                "reconnects": st["reconnects"],
                "reconnect_ms": round(st["reconnect_ms"], 3),
                "last_state": st["last_state"],
            }
    causal = None
    freshness = None
    if chains:
        # fold token-keyed chains into bridged groups (union-find roots)
        groups: dict = {}
        for tok, ch in chains.items():
            g = groups.get(_find(tok))
            if g is None:
                groups[_find(tok)] = g = {
                    "links": defaultdict(list), "bounds": {},
                    "t0": None, "t1": None, "tokens": []}
            g["tokens"].append(tok)
            for name, durs in ch["links"].items():
                g["links"][name].extend(durs)
            for name, b in ch["bounds"].items():
                gb = g["bounds"].get(name)
                if gb is None:
                    g["bounds"][name] = list(b)
                else:
                    gb[0] = min(gb[0], b[0])
                    gb[1] = max(gb[1], b[1])
                    if len(gb) > 2 and len(b) > 2:
                        gb[2] = min(gb[2], b[2])
            if ch["t0"] is not None:
                g["t0"] = ch["t0"] if g["t0"] is None \
                    else min(g["t0"], ch["t0"])
                g["t1"] = ch["t1"] if g["t1"] is None \
                    else max(g["t1"], ch["t1"])
        # the canonical replication chain; a group carrying all three
        # links is "complete" — per-link attribution is computed over
        # those, so partial chains (dropped shipment, wrapped ring)
        # can't skew the hop shares
        chain_links = ("ship_segment", "net_send", "replica_replay")
        complete = [g for g in groups.values()
                    if all(name in g["links"] for name in chain_links)]
        full = [g for g in groups.values()
                if all(name in g["links"] for name in FULL_CHAIN)]
        link_us: dict = defaultdict(float)
        link_count: dict = defaultdict(int)
        e2e_us_list = []
        for g in complete:
            e2e_us_list.append((g["t1"] or 0.0) - (g["t0"] or 0.0))
            for name, durs in g["links"].items():
                link_us[name] += sum(durs)
                link_count[name] += len(durs)
        total_link_us = sum(link_us.values())
        causal = {
            "chains": len(chains),
            "groups": len(groups),
            "complete_chains": len(complete),
            "full_chains": len(full),
            "links": {
                name: {"spans": link_count[name],
                       "total_ms": round(us / 1e3, 3),
                       "share": (round(us / total_link_us, 4)
                                 if total_link_us else 0.0)}
                for name, us in sorted(link_us.items())},
            "chain_e2e_p50_us": round(percentile(e2e_us_list, 50), 3),
            "chain_e2e_p99_us": round(percentile(e2e_us_list, 99), 3),
            "span_names": sorted({name for ch in chains.values()
                                  for name in ch["links"]}),
        }
        if require_chain:
            causal["required_chains"] = sum(
                1 for g in groups.values()
                if all(name in g["links"] for name in require_chain))
        # freshness decomposes ONE write's journey, so it is computed
        # over token-keyed chains, never bridged groups (see
        # FRESHNESS_SPANS for why)
        freshness = _freshness_summary(
            [(tok, ch) for tok, ch in chains.items()
             if all(name in ch["links"] for name in FRESHNESS_SPANS)])
    tiles = None
    if any(st["tiles"] for k, st in tiles_acc.items()
           if "tiles" in st) or tiles_acc["tile_ship"]["units"] \
            or tiles_acc["tile_ship"]["rejects"]:
        for st in tiles_acc.values():
            st["ms"] = round(st["ms"], 3)
        tiles = tiles_acc
    failover = None
    if failover_events or fence_rejects:
        failover = {
            "promotions": sum(1 for e in failover_events
                              if e["event"] == "elect"),
            "elect_ms": round(sum(e["ms"] for e in failover_events
                                  if e["event"] == "elect"), 3),
            "replay_ms": round(sum(e["ms"] for e in failover_events
                                   if e["event"] == "replay"), 3),
            "fence_rejects": dict(sorted(fence_rejects.items())),
            "events": failover_events,
        }
    return {
        "schema": "reflow.trace_inspect/2",
        "trace_file": paths[0],
        "trace_files": paths,
        "files": files,
        "events": sum(len(d) for d in by_name.values()),
        "tracks": len(tracks),
        "freshness": freshness,
        "durability": durability,
        "failover": failover,
        "window_dispatch_frac": window_dispatch_frac,
        "stage_overlap_frac": stage_overlap_frac,
        "dispatch_by_depth": dispatch_by_depth,
        "per_device": per_device,
        "replication": replication,
        "tiles": tiles,
        "network": network,
        "causal": causal,
        "control_actions": control_actions,
        "spans": spans,
        "tickets": len(tickets),
        "ticket_e2e_p50_us": round(percentile(e2e, 50), 3),
        "ticket_e2e_p99_us": round(percentile(e2e, 99), 3),
        "ticket_stages": stage_summary,
        "critical_path": critical_path,
        "decomposition_max_dev_frac": round(max_dev, 6),
    }


def _print_human(s: dict) -> None:
    print(f"{s['trace_file']}: {s['events']} span(s) on "
          f"{s['tracks']} track(s)")
    print(f"{'span':<16} {'count':>7} {'p50_us':>12} {'p99_us':>12} "
          f"{'total_ms':>10}")
    for name, d in s["spans"].items():
        print(f"{name:<16} {d['count']:>7} {d['p50_us']:>12.1f} "
              f"{d['p99_us']:>12.1f} {d['total_ms']:>10.2f}")
    dur = s["durability"]
    if dur["onpath_fsyncs"] or dur["offpath_fsyncs"]:
        print(f"durability: {dur['offpath_fsyncs']} fsync(s) off the "
              f"dispatch path ({dur['offpath_fsync_frac']:.0%} of fsync "
              f"time), {dur['onpath_fsyncs']} inline; mean group "
              f"coverage {dur['fsync_covered_mean']:.1f}")
    if s["window_dispatch_frac"]:
        print(f"window dispatch fraction: "
              f"{s['window_dispatch_frac']:.0%} of commit-window time "
              f"was device dispatch")
    if s.get("stage_overlap_frac"):
        print(f"stage overlap: {s['stage_overlap_frac']:.0%} of host "
              f"staging time ran while a window was in flight")
    if s.get("dispatch_by_depth"):
        occ = ", ".join(f"depth {d}: {n}"
                        for d, n in s["dispatch_by_depth"].items())
        print(f"dispatch occupancy: {occ}")
    if s.get("per_device"):
        print(f"{'device':<12} {'dispatches':>11} {'busy_ms':>10} "
              f"{'share':>8}")
        for dev, d in s["per_device"].items():
            print(f"{dev:<12} {d['dispatches']:>11} {d['busy_ms']:>10.2f} "
                  f"{100 * d['share']:>7.1f}%")
    rep = s.get("replication")
    if rep:
        print(f"replication: max lag {rep['max_lag_ticks']} tick(s), "
              f"final lag {rep['final_lag_ticks']} tick(s)")
        for name, d in rep["replicas"].items():
            print(f"  replica {name}: {d['shipments']} shipment(s) "
                  f"{d['records_applied']} record(s) applied in "
                  f"{d['replay_ms']:.2f}ms, horizon {d['horizon']}, "
                  f"lag {d['lag_ticks']} (max {d['max_lag_ticks']})")
        for name, d in rep["ship"].items():
            print(f"  ship->{name}: {d['shipments']} shipment(s) "
                  f"{d['bytes']} byte(s) in {d['ship_ms']:.2f}ms, "
                  f"{d['nacks']} nack(s)")
    ti = s.get("tiles")
    if ti:
        ct, kt, sh = ti["compact_tile"], ti["ckpt_tile"], ti["tile_ship"]
        if ct["tiles"]:
            print(f"tiles: compacted {ct['tiles']} tile(s) "
                  f"({ct['parts']} part record(s)) in {ct['ms']:.2f}ms, "
                  f"max resident {ct['max_resident_bytes']} byte(s)")
        if kt["tiles"]:
            print(f"tiles: checkpointed {kt['tiles']} tile frame(s) "
                  f"({kt['full']} full, {kt['delta']} delta) in "
                  f"{kt['ms']:.2f}ms, max frame {kt['max_bytes']} "
                  f"byte(s)")
        if sh["units"] or sh["rejects"]:
            print(f"tiles: shipped {sh['units']} ckpt unit(s) "
                  f"{sh['bytes']} byte(s) in {sh['ms']:.2f}ms, "
                  f"{sh['retries']} retried, {sh['rejects']} rejected")
    net = s.get("network")
    if net:
        for link, d in net.items():
            ops = ", ".join(f"{k}={v}" for k, v in d["ops"].items())
            print(f"  net/{link}: {d['sends']} send(s) "
                  f"({d['send_failures']} failed) in "
                  f"{d['send_ms']:.2f}ms [{ops}]; "
                  f"{d['reconnects']}/{d['reconnect_attempts']} "
                  f"reconnect(s) in {d['reconnect_ms']:.2f}ms; "
                  f"state={d['last_state']}")
    ca = s.get("causal")
    if ca:
        print(f"causal chains: {ca['complete_chains']}/"
              f"{ca.get('groups', ca['chains'])} replication-complete, "
              f"{ca.get('full_chains', 0)} full submit→deliver — "
              f"e2e p50 {ca['chain_e2e_p50_us']:.1f}us "
              f"p99 {ca['chain_e2e_p99_us']:.1f}us")
        for name, d in ca["links"].items():
            print(f"  link {name}: {d['spans']} span(s) "
                  f"{d['total_ms']:.2f}ms ({100 * d['share']:.1f}% of "
                  f"chain link time)")
    fr = s.get("freshness")
    if fr:
        print(f"freshness: {fr['chains']} full chain(s) — ack→deliver "
              f"p50 {fr['e2e_p50_us']:.1f}us p99 {fr['e2e_p99_us']:.1f}us "
              f"(tiling deviation max {100 * fr['max_dev_frac']:.2f}%)")
        for name in FRESHNESS_STAGES:
            d = fr["stages"][name]
            print(f"  {name:<12} p50 {d['p50_us']:>10.1f}us "
                  f"p99 {d['p99_us']:>10.1f}us "
                  f"{100 * d['mean_share']:>6.1f}%")
    fo = s.get("failover")
    if fo:
        rej = ", ".join(f"{v} {k}(s)"
                        for k, v in fo["fence_rejects"].items()) or "none"
        print(f"failover: {fo['promotions']} promotion(s) — elect "
              f"{fo['elect_ms']:.2f}ms, replay {fo['replay_ms']:.2f}ms; "
              f"fence rejects: {rej}")
        for e in fo["events"]:
            if e["event"] == "elect":
                print(f"  epoch {e['epoch']}: elected {e['winner']} "
                      f"({e['reason']}), drained "
                      f"{e['drained_bytes']} byte(s) in {e['ms']:.2f}ms")
            else:
                print(f"  epoch {e['epoch']}: replayed "
                      f"{e['replayed_pushes']} push(es) / "
                      f"{e['replayed_ticks']} tick(s) to horizon "
                      f"{e['horizon']} in {e['ms']:.2f}ms")
    if s["control_actions"]:
        acts = ", ".join(f"{k}={v}"
                         for k, v in s["control_actions"].items())
        print(f"control actions: {acts}")
    if not s["tickets"]:
        print("no sampled tickets in this trace "
              "(REFLOW_TRACE_SAMPLE too high, or no serve traffic)")
        return
    print(f"\n{s['tickets']} sampled ticket(s): end-to-end "
          f"p50 {s['ticket_e2e_p50_us']:.1f}us "
          f"p99 {s['ticket_e2e_p99_us']:.1f}us "
          f"(stage-sum deviation max "
          f"{100 * s['decomposition_max_dev_frac']:.2f}%)")
    print(f"{'stage':<12} {'p50_us':>12} {'p99_us':>12} {'share':>8}")
    for name in s["critical_path"]:
        d = s["ticket_stages"][name]
        print(f"{name:<12} {d['p50_us']:>12.1f} {d['p99_us']:>12.1f} "
              f"{100 * d['mean_share']:>7.1f}%")
    print(f"critical path: {' > '.join(s['critical_path'][:3])}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", nargs="+",
                    help="trace file(s); several are merged onto one "
                         "timeline via their baseTimeS anchors")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON line")
    ap.add_argument("--require-chain", metavar="SPANS",
                    help="comma-separated span names; exit 1 unless at "
                         "least one causal chain carries them all")
    args = ap.parse_args(argv)
    want = [w.strip() for w in (args.require_chain or "").split(",")
            if w.strip()]
    summary = inspect(args.trace, require_chain=want or None)
    if args.json:
        print(json.dumps(summary))
    else:
        _print_human(summary)
    if want:
        ca = summary.get("causal")
        got = ca.get("required_chains", 0) if ca else 0
        if not got:
            print(f"require-chain FAILED: no causal chain carries all "
                  f"of {want} (chains={ca['chains'] if ca else 0})",
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
