#!/usr/bin/env python3
"""Per-stage latency breakdown + critical path of a reflow trace.

Usage::

    python tools/trace_inspect.py trace.json           # human report
    python tools/trace_inspect.py trace.json --json    # machine summary

Input is the Chrome trace-event JSON written by
``reflow_tpu.obs.export_chrome_trace()`` (either the
``{"traceEvents": [...]}`` object or a bare event array). The report
has two halves:

- **spans**: p50/p99/total for every named span across all tracks
  (windows, ticks, WAL appends/fsyncs, device dispatches);
- **tickets**: the sampled tickets' end-to-end latency decomposed into
  the six pipeline stages (admission → coalesce → sched_delay →
  execute → fsync → resolve), with the **critical path** — stages
  ranked by their mean share of end-to-end latency — and the worst
  decomposition deviation (stage sums are tiled, so this should sit at
  ~0%; large values mean a clock or export bug).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reflow_tpu.obs.export import ticket_timelines  # noqa: E402
from reflow_tpu.obs.trace import STAGES  # noqa: E402
from reflow_tpu.utils.metrics import percentile  # noqa: E402


def load_events(path: str) -> list:
    with open(path) as f:
        raw = json.load(f)
    return raw["traceEvents"] if isinstance(raw, dict) else raw


def inspect(path: str) -> dict:
    """Summarize one trace file; the dict is the ``--json`` output."""
    events = load_events(path)
    by_name: dict = defaultdict(list)
    tracks = set()
    for ev in events:
        if ev.get("ph") == "X":
            by_name[ev.get("name", "?")].append(float(ev.get("dur", 0.0)))
            tracks.add(ev.get("tid"))
    spans = {
        name: {"count": len(durs),
               "p50_us": round(percentile(durs, 50), 3),
               "p99_us": round(percentile(durs, 99), 3),
               "total_ms": round(sum(durs) / 1e3, 3)}
        for name, durs in sorted(by_name.items())}

    tickets = ticket_timelines(events)
    e2e = [t["e2e_us"] for t in tickets.values()]
    stage_durs = {s: [t["stages"].get(s, 0.0) for t in tickets.values()]
                  for s in STAGES}
    mean_e2e = sum(e2e) / len(e2e) if e2e else 0.0
    stage_summary = {}
    for s in STAGES:
        durs = stage_durs[s]
        mean = sum(durs) / len(durs) if durs else 0.0
        stage_summary[s] = {
            "p50_us": round(percentile(durs, 50), 3),
            "p99_us": round(percentile(durs, 99), 3),
            "mean_share": round(mean / mean_e2e, 4) if mean_e2e else 0.0,
        }
    critical_path = sorted(
        STAGES, key=lambda s: stage_summary[s]["mean_share"],
        reverse=True)
    max_dev = 0.0
    for t in tickets.values():
        if t["e2e_us"] > 0:
            max_dev = max(max_dev, abs(t["sum_us"] - t["e2e_us"])
                          / t["e2e_us"])
    return {
        "schema": "reflow.trace_inspect/1",
        "trace_file": path,
        "events": sum(len(d) for d in by_name.values()),
        "tracks": len(tracks),
        "spans": spans,
        "tickets": len(tickets),
        "ticket_e2e_p50_us": round(percentile(e2e, 50), 3),
        "ticket_e2e_p99_us": round(percentile(e2e, 99), 3),
        "ticket_stages": stage_summary,
        "critical_path": critical_path,
        "decomposition_max_dev_frac": round(max_dev, 6),
    }


def _print_human(s: dict) -> None:
    print(f"{s['trace_file']}: {s['events']} span(s) on "
          f"{s['tracks']} track(s)")
    print(f"{'span':<16} {'count':>7} {'p50_us':>12} {'p99_us':>12} "
          f"{'total_ms':>10}")
    for name, d in s["spans"].items():
        print(f"{name:<16} {d['count']:>7} {d['p50_us']:>12.1f} "
              f"{d['p99_us']:>12.1f} {d['total_ms']:>10.2f}")
    if not s["tickets"]:
        print("no sampled tickets in this trace "
              "(REFLOW_TRACE_SAMPLE too high, or no serve traffic)")
        return
    print(f"\n{s['tickets']} sampled ticket(s): end-to-end "
          f"p50 {s['ticket_e2e_p50_us']:.1f}us "
          f"p99 {s['ticket_e2e_p99_us']:.1f}us "
          f"(stage-sum deviation max "
          f"{100 * s['decomposition_max_dev_frac']:.2f}%)")
    print(f"{'stage':<12} {'p50_us':>12} {'p99_us':>12} {'share':>8}")
    for name in s["critical_path"]:
        d = s["ticket_stages"][name]
        print(f"{name:<12} {d['p50_us']:>12.1f} {d['p99_us']:>12.1f} "
              f"{100 * d['mean_share']:>7.1f}%")
    print(f"critical path: {' > '.join(s['critical_path'][:3])}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON line")
    args = ap.parse_args(argv)
    summary = inspect(args.trace)
    if args.json:
        print(json.dumps(summary))
    else:
        _print_human(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
