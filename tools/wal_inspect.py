#!/usr/bin/env python3
"""Dump / verify a write-ahead delta log directory (reflow_tpu.wal).

Usage::

    python tools/wal_inspect.py <wal_dir>            # human dump + summary
    python tools/wal_inspect.py <wal_dir> --verify   # exit 1 on corruption
    python tools/wal_inspect.py <wal_dir> --json     # machine summary

Per record: position (segment:offset), kind, tick horizon, source node,
batch id, live row count and net weight for pushes. A tolerated torn
tail (partial final record — what a mid-write kill leaves) is reported
but is NOT corruption; a bad frame in a sealed segment is, and fails
``--verify``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from reflow_tpu.wal.log import WalError, list_segments, scan_wal  # noqa: E402


def _describe(rec: dict) -> str:
    kind = rec.get("kind", "?")
    if kind == "push":
        w = np.asarray(rec["weights"])
        ids = rec.get("batch_ids")
        if ids:
            # a coalesced frontend feed batch (wal/durable.tick_many):
            # these micro-batch ids are ONE replay unit — recovery
            # re-folds all of them or dedups all of them, never a subset
            shown = ", ".join(repr(i) for i in ids[:3])
            if len(ids) > 3:
                shown += f", … +{len(ids) - 3} more"
            idpart = f"ids[{len(ids)} coalesced, atomic]=[{shown}]"
        else:
            idpart = f"id={rec['batch_id']!r}"
        return (f"push  tick={rec['tick']:<6} src={rec['node_name']!r}"
                f"(#{rec['node']}) {idpart} rows={len(w)} "
                f"net_weight={int(w.sum())}")
    if kind == "tick":
        return f"tick  tick={rec['tick']}"
    if kind == "ckpt":
        return f"ckpt  tick={rec['tick']} path={rec.get('path', '?')!r}"
    return f"{kind}?  {sorted(rec)}"


def inspect(wal_dir: str, *, verbose: bool = True,
            ckpt_dir: str = None) -> dict:
    """Scan + summarize; the dict is the machine-readable result."""
    segs = list_segments(wal_dir)
    records, torn = scan_wal(wal_dir)
    counts: dict = {}
    rows = ticks = 0
    # group-commit shape: a coalesced frontend window is appended as one
    # run of push records between tick marks (durable.tick_many), so the
    # on-disk commit-window sizes are the push-run lengths; replay units
    # are the per-record batch_ids lists (atomic: all folded or all
    # deduped)
    coalesced_records = coalesced_ids = max_ids = 0
    push_runs: list = []
    run = 0
    per_seg: dict = {
        seg: {"segment": seg, "bytes": os.path.getsize(path),
              "records": 0, "pushes": 0, "rows": 0, "micro_batches": 0,
              "epoch": 0}
        for seg, path in segs}
    max_epoch = 0
    for pos, rec in records:
        kind = rec.get("kind", "?")
        counts[kind] = counts.get(kind, 0) + 1
        ep = int(rec.get("epoch", 0) or 0)
        max_epoch = max(max_epoch, ep)
        seg = per_seg.get(pos.segment)
        if seg is not None:
            seg["records"] += 1
            seg["epoch"] = max(seg["epoch"], ep)
        if kind == "push":
            n = len(np.asarray(rec["weights"]))
            rows += n
            run += 1
            ids = rec.get("batch_ids")
            if seg is not None:
                seg["pushes"] += 1
                seg["rows"] += n
                seg["micro_batches"] += len(ids) if ids else 1
            if ids:
                coalesced_records += 1
                coalesced_ids += len(ids)
                max_ids = max(max_ids, len(ids))
        else:
            if run:
                push_runs.append(run)
            run = 0
        if kind == "tick":
            ticks = max(ticks, rec["tick"])
        if verbose:
            print(f"  {pos.segment:08d}:{pos.offset:<10} {_describe(rec)}")
    if run:
        push_runs.append(run)
    win = np.asarray(push_runs, dtype=float)
    shipping = _ship_summary(wal_dir, per_seg)
    compaction = _compact_summary(wal_dir, per_seg)
    ckpt_roots = []
    if ckpt_dir is not None:
        ckpt_roots.append(ckpt_dir)
    for _pos, rec in records:
        p = rec.get("path")
        if (rec.get("kind") == "ckpt" and p
                and p not in ckpt_roots):
            ckpt_roots.append(p)
    chains = _chain_summary(ckpt_roots)
    return {
        # same schema family as reflow_tpu.obs snapshots / trace_inspect
        "schema": "reflow.wal_inspect/1",
        "wal_dir": wal_dir,
        "segments": len(segs),
        "bytes": sum(os.path.getsize(p) for _s, p in segs),
        "records": len(records),
        "record_kinds": counts,
        "push_rows": rows,
        "last_tick_mark": ticks,
        "coalesced_push_records": coalesced_records,
        "coalesced_micro_batches": coalesced_ids,
        "max_replay_unit_ids": max_ids,
        "commit_windows": len(push_runs),
        "commit_window_max_pushes": max(push_runs) if push_runs else 0,
        "commit_window_pushes": push_runs,
        "commit_window_p50_pushes": (
            float(np.percentile(win, 50)) if len(win) else 0.0),
        "commit_window_p95_pushes": (
            float(np.percentile(win, 95)) if len(win) else 0.0),
        "segments_detail": [per_seg[s] for s in sorted(per_seg)],
        "shipping": shipping,
        "compaction": compaction,
        "checkpoint_chain": chains,
        "tiles": _tiles_summary(wal_dir, compaction, chains),
        "epochs": _epoch_summary(wal_dir, max_epoch),
        "torn_tail": torn._asdict() if torn is not None else None,
    }


def _tiles_summary(wal_dir: str, compaction, chains):
    """Key-range tiled maintenance state (REFLOW_TILE_BYTES > 0): tiled
    compaction ranges (count, budget, peak resident bytes, per-tile fold
    generations), interrupted-pass recovery sidecars awaiting a
    roll-forward resume (``*.compact.progress``), and tiled checkpoint
    chains. None when nothing in this log was ever tiled."""
    interrupted = []
    try:
        names = sorted(os.listdir(wal_dir))
    except OSError:
        names = []
    for n in names:
        if not n.endswith(".compact.progress"):
            continue
        path = os.path.join(wal_dir, n)
        try:
            with open(path) as f:
                prog = json.load(f)
        except (OSError, ValueError) as e:
            interrupted.append({"sidecar": n, "error": str(e)})
            continue
        interrupted.append({
            "sidecar": n,
            "attempt": prog.get("attempt"),
            "budget": prog.get("budget"),
            "tiles_total": len(prog.get("plan") or []),
            "tiles_done": len(prog.get("done") or []),
        })
    ranges = []
    count = 0
    peak = 0
    budget = 0
    if isinstance(compaction, dict):
        for ent in compaction.get("ranges", []):
            ti = ent.get("tiles")
            if not ti:
                continue
            ranges.append({"out": ent["out"], "n": ti.get("n"),
                           "budget": ti.get("budget"),
                           "peak_tile_bytes": ti.get("peak_tile_bytes"),
                           "gens": ti.get("gens"),
                           "resumed_tiles": ti.get("resumed_tiles")})
            count += int(ti.get("n") or 0)
            peak = max(peak, int(ti.get("peak_tile_bytes") or 0))
            budget = max(budget, int(ti.get("budget") or 0))
    chain_tiles = []
    for ch in chains or []:
        ti = ch.get("tiles")
        if ti:
            chain_tiles.append({"root": ch.get("root"), **ti})
            budget = max(budget, int(ti.get("budget") or 0))
    if not ranges and not interrupted and not chain_tiles:
        return None
    return {
        "budget": budget,
        "tile_count": count,
        "peak_tile_bytes": peak,
        "compact_ranges": ranges,
        "interrupted": interrupted or None,
        "chains": chain_tiles or None,
    }


def _compact_summary(wal_dir: str, per_seg: dict):
    """Merge the compactor's persisted manifest (wal/compact.py writes
    ``compact-manifest.json`` next to the segments) into the summary
    and stamp each live segment's compaction status. None when this log
    was never compacted."""
    path = os.path.join(wal_dir, "compact-manifest.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return {"error": f"unreadable compact-manifest.json: {e}"}
    ranges = manifest.get("ranges", [])
    covered = 0
    for ent in ranges:
        a, b = ent["covers"]
        covered += b - a + 1
        for seg in per_seg.values():
            s = seg["segment"]
            if s == ent["out"]:
                seg["compacted"] = {"covers": [a, b], "gen": ent["gen"],
                                    "records_in": ent["records_in"],
                                    "records_out": ent["records_out"]}
            elif a < s <= b:
                # still on disk inside a folded range: superseded by
                # the out segment, awaiting (or surviving a crashed)
                # unlink — replay-harmless, its ids dedup away
                seg["superseded_by"] = ent["out"]
    return {
        "gen": manifest.get("gen"),
        "ranges": ranges,
        "segments_covered": covered,
        "reclaimed_bytes": manifest.get("reclaimed_bytes", 0),
    }


def _chain_summary(roots: list):
    """Incremental-checkpoint chains reachable from this log: every
    ``ckpt`` record's path (plus an explicit ``--ckpt``) that holds a
    ``chain.json`` manifest (utils/checkpoint.py). None when no chain
    is found — a legacy full checkpoint has no chain to report."""
    chains = []
    for root in roots:
        mpath = os.path.join(root, "chain.json")
        if not os.path.exists(mpath):
            continue
        try:
            with open(mpath) as f:
                m = json.load(f)
        except (OSError, ValueError) as e:
            chains.append({"root": root,
                           "error": f"unreadable chain.json: {e}"})
            continue
        deltas = m.get("deltas", [])
        delta_bytes = 0
        missing = []
        for d in deltas:
            try:
                delta_bytes += os.path.getsize(os.path.join(root, d))
            except OSError:
                missing.append(d)
        chains.append({
            "root": root,
            "base": m.get("base"),
            "deltas": len(deltas),
            "delta_bytes": delta_bytes,
            "horizon": m.get("horizon"),
            "wal_pos": m.get("wal_pos"),
            "saves": m.get("saves"),
            "tiles": m.get("tiles"),
            "broken_links": missing,
        })
    return chains or None


def _epoch_summary(wal_dir: str, record_max: int):
    """Failover lineage: the highest epoch stamped into any record,
    merged with the ``fence-state.json`` sidecar a fenced (zombie)
    writer leaves behind. ``fenced`` means a NEWER epoch exists — this
    log's writer must never append again."""
    out = {"record_max": record_max, "epoch": record_max,
           "fenced_by": None, "fenced": False, "rejected_appends": 0}
    path = os.path.join(wal_dir, "fence-state.json")
    try:
        with open(path) as f:
            state = json.load(f)
    except OSError:
        return out
    except ValueError as e:
        out["error"] = f"unreadable fence-state.json: {e}"
        return out
    out["epoch"] = max(record_max, int(state.get("epoch") or 0))
    fb = state.get("fenced_by")
    out["fenced_by"] = int(fb) if fb is not None else None
    out["fenced"] = fb is not None and int(fb) > out["epoch"]
    out["rejected_appends"] = int(state.get("rejected_appends") or 0)
    return out


def _ship_summary(wal_dir: str, per_seg: dict):
    """Merge the shipper's persisted watermarks (wal/ship.py writes
    ``ship-state.json`` next to the segments) into the summary and stamp
    each segment's ship status: how many followers have fully fetched
    it. None when this log has never been shipped."""
    path = os.path.join(wal_dir, "ship-state.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            state = json.load(f)
    except (OSError, ValueError) as e:
        return {"error": f"unreadable ship-state.json: {e}"}
    followers = state.get("followers", {})
    cursors = [tuple(f["shipped"]) for f in followers.values()
               if f.get("shipped")]
    for seg in per_seg.values():
        # a follower has the whole segment iff its cursor moved past it
        seg["shipped_followers"] = sum(
            1 for c in cursors if c[0] > seg["segment"]
            or (c[0] == seg["segment"] and c[1] >= seg["bytes"]))
        seg["shipped_fully"] = (len(cursors) > 0
                                and seg["shipped_followers"] == len(cursors))
    return {
        "horizon": state.get("horizon"),
        "leader_tick": state.get("leader_tick"),
        "bytes_total": state.get("bytes_total"),
        "shipments": state.get("shipments"),
        "nacks": state.get("nacks"),
        "retransmit_bytes": state.get("retransmit_bytes"),
        "link_stalls": state.get("link_stalls"),
        # per-follower wire state (reconnect policy snapshot merged with
        # shipper-side retransmit/stall counters); absent for logs only
        # ever shipped to in-process followers
        "transport": state.get("transport"),
        "followers": {
            name: {"shipped": f.get("shipped"),
                   "applied_horizon": f.get("applied_horizon"),
                   "lag_ticks": max(0, (state.get("leader_tick") or 0)
                                    - (f.get("applied_horizon") or 0)),
                   "bytes_total": f.get("bytes_total"),
                   "nacks": f.get("nacks")}
            for name, f in followers.items()},
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("wal_dir")
    ap.add_argument("--verify", action="store_true",
                    help="exit 1 on sealed-segment corruption")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON line (no dump)")
    ap.add_argument("--ckpt", default=None,
                    help="checkpoint/chain directory to summarize (in "
                         "addition to any 'ckpt' record paths)")
    args = ap.parse_args(argv)
    try:
        summary = inspect(args.wal_dir, verbose=not args.json,
                          ckpt_dir=args.ckpt)
    except WalError as e:
        print(f"CORRUPT: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(summary))
    else:
        torn = summary["torn_tail"]
        print(f"{summary['segments']} segment(s), {summary['records']} "
              f"record(s), {summary['bytes']} bytes; kinds="
              f"{summary['record_kinds']} push_rows={summary['push_rows']} "
              f"last_tick_mark={summary['last_tick_mark']}")
        if summary["coalesced_push_records"]:
            print(f"coalesced group-commit: "
                  f"{summary['coalesced_push_records']} record(s) "
                  f"carrying {summary['coalesced_micro_batches']} "
                  f"micro-batch ids (largest replay unit "
                  f"{summary['max_replay_unit_ids']}); "
                  f"{summary['commit_windows']} commit window(s), "
                  f"largest {summary['commit_window_max_pushes']} "
                  f"push(es)")
        ship = summary["shipping"]
        for seg in summary["segments_detail"]:
            shipped = ""
            if ship and "followers" in ship:
                shipped = (f" shipped={seg.get('shipped_followers', 0)}/"
                           f"{len(ship['followers'])} follower(s)")
            comp = seg.get("compacted")
            if comp:
                shipped += (f" compacted[{comp['covers'][0]}"
                            f"..{comp['covers'][1]} gen={comp['gen']} "
                            f"{comp['records_in']}→"
                            f"{comp['records_out']} rec]")
            if seg.get("superseded_by") is not None:
                shipped += f" SUPERSEDED by {seg['superseded_by']:08d}"
            print(f"segment {seg['segment']:08d}: {seg['bytes']:>8} bytes "
                  f"{seg['records']:>5} record(s) {seg['pushes']:>5} "
                  f"push(es) {seg['rows']:>7} row(s) "
                  f"{seg['micro_batches']:>5} micro-batch(es){shipped}")
        compaction = summary["compaction"]
        if compaction and "ranges" in compaction:
            print(f"compaction: gen={compaction['gen']} "
                  f"{len(compaction['ranges'])} range(s) covering "
                  f"{compaction['segments_covered']} segment(s), "
                  f"reclaimed={compaction['reclaimed_bytes']} bytes")
        for ch in summary["checkpoint_chain"] or []:
            if "error" in ch:
                print(f"chain {ch['root']}: {ch['error']}")
                continue
            broken = (f" BROKEN links: {ch['broken_links']}"
                      if ch["broken_links"] else "")
            print(f"chain {ch['root']}: base={ch['base']} "
                  f"+{ch['deltas']} delta(s) "
                  f"({ch['delta_bytes']} bytes) "
                  f"horizon={ch['horizon']} "
                  f"wal_pos={ch['wal_pos']}{broken}")
        tiles = summary["tiles"]
        if tiles:
            print(f"tiles: budget={tiles['budget']} "
                  f"count={tiles['tile_count']} "
                  f"peak_tile_bytes={tiles['peak_tile_bytes']}")
            for rng_ in tiles["compact_ranges"]:
                print(f"  compact out={rng_['out']:08d}: "
                      f"{rng_['n']} tile(s) "
                      f"peak={rng_['peak_tile_bytes']} "
                      f"gens={rng_['gens']} "
                      f"resumed={rng_['resumed_tiles']}")
            for it in tiles["interrupted"] or []:
                if "error" in it:
                    print(f"  INTERRUPTED {it['sidecar']}: {it['error']}")
                else:
                    print(f"  INTERRUPTED {it['sidecar']}: "
                          f"{it['tiles_done']}/{it['tiles_total']} "
                          f"tile(s) done, attempt={it['attempt']} — "
                          f"next pass resumes without refolding")
            for ct in tiles["chains"] or []:
                print(f"  chain {ct['root']}: {ct['count']} tile(s) "
                      f"peak={ct['peak_tile_bytes']}")
        if ship and "followers" in ship:
            print(f"shipping: horizon={tuple(ship['horizon'])} "
                  f"leader_tick={ship['leader_tick']} "
                  f"bytes_total={ship['bytes_total']} "
                  f"nacks={ship['nacks']}")
            for fname, f in sorted(ship["followers"].items()):
                print(f"  follower {fname}: shipped="
                      f"{tuple(f['shipped']) if f['shipped'] else None} "
                      f"applied_horizon={f['applied_horizon']} "
                      f"lag_ticks={f['lag_ticks']} "
                      f"bytes={f['bytes_total']} nacks={f['nacks']}")
        if ship and ship.get("transport"):
            for fname, t in sorted(ship["transport"].items()):
                print(f"  transport {fname}: state={t.get('state')} "
                      f"reconnects={t.get('reconnects')} "
                      f"retransmit_bytes={t.get('retransmit_bytes')} "
                      f"link_stalls={t.get('link_stalls')} "
                      f"last_backoff_s={t.get('last_backoff_s')}")
        ep = summary["epochs"]
        if ep["epoch"] or ep["fenced_by"] is not None:
            status = (f" FENCED by epoch {ep['fenced_by']} — zombie "
                      f"writer, {ep['rejected_appends']} append(s) "
                      f"rejected" if ep["fenced"] else "")
            print(f"epochs: current={ep['epoch']} "
                  f"record_max={ep['record_max']}{status}")
        if torn:
            print(f"torn tail (tolerated): segment {torn['segment']} @ "
                  f"{torn['offset']}: {torn['reason']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
