#!/usr/bin/env python3
"""Measure whether staged (topo-partitioned) execution can overlap on
this runtime — the evidence behind the claim-bounding in
``parallel/topo.py`` (VERDICT r4 weak #4).

Two measurements:

1. **Raw runtime overlap**: dispatch one latency-bound program on device
   0, then the same program on devices 0 AND 1 back-to-back, and compare
   walls. Ratio ~1.0 = the runtime truly executes different devices'
   programs concurrently (pipelining can win); ratio ~2.0 = execution is
   serial across devices (no schedule can overlap anything).
2. **Framework staged-vs-single**: the two-stage compute-bound graph
   (heavy params-Map per stage -> keyed Reduce) driven for K streaming
   ticks on 1 device vs 2 devices via ``StagedTpuExecutor``.

Measured on this environment (2026-07-30, 8-virtual-device CPU mesh,
``xla_force_host_platform_device_count``): raw overlap ratio **2.32**
(fully serial — the host CPU platform runs one device program at a
time and a single program already uses the whole intra-op thread pool),
and accordingly staged-vs-single = **0.95-1.04x** (parity; the
device_put handoffs cost nothing measurable). The pipeline win requires
genuinely concurrent devices — real distinct chips — which this
environment cannot provide (the tunnel exposes ONE TPU chip). The
staged executor's value here is therefore state-capacity partitioning
(per-stage HBM) with bounded handoff overhead, not throughput.

Usage: PYTHONPATH=. python tools/staged_pipeline_probe.py
"""

import os
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def probe_raw_overlap(chain=400, d=64):
    def body(x):
        for _ in range(chain):
            x = jnp.tanh(x @ x)
        return x

    d0, d1 = jax.devices()[:2]
    f0 = jax.jit(body, device=d0)
    f1 = jax.jit(body, device=d1)
    x0 = jax.device_put(jnp.eye(d) * 0.5, d0)
    x1 = jax.device_put(jnp.eye(d) * 0.5, d1)
    f0(x0).block_until_ready()
    f1(x1).block_until_ready()
    t0 = time.perf_counter()
    f0(x0).block_until_ready()
    one = time.perf_counter() - t0
    t0 = time.perf_counter()
    a, b = f0(x0), f1(x1)
    a.block_until_ready()
    b.block_until_ready()
    both = time.perf_counter() - t0
    return one, both, both / one


def probe_staged(n_dev, K=64, D=512, rows=256, ticks=10, chain=6):
    from reflow_tpu import DirtyScheduler, FlowGraph
    from reflow_tpu.delta import DeltaBatch, Spec
    from reflow_tpu.parallel.topo import StagedTpuExecutor

    def heavy(p, v):
        for _ in range(chain):
            v = jnp.tanh(v @ p)
        return v

    g = FlowGraph("pipe")
    src = g.source("x", Spec((D,), np.float32, key_space=K))
    rng = np.random.default_rng(0)
    W0 = (rng.standard_normal((D, D)) * 0.05).astype(np.float32)
    W1 = (rng.standard_normal((D, D)) * 0.05).astype(np.float32)
    m0 = g.map(src, heavy, vectorized=True, params=W0, name="m0")
    m1 = g.map(m0, heavy, vectorized=True, params=W1, name="m1")
    gb = g.group_by(m1, key_fn=lambda k, v: k % K, vectorized=True)
    red = g.reduce(gb, "sum", name="agg")
    m0.stage = 0
    for n in (m1, gb, red):
        n.stage = 1

    ex = StagedTpuExecutor(devices=jax.devices()[:n_dev])
    sched = DirtyScheduler(g, ex)
    rng = np.random.default_rng(7)

    def batch():
        return DeltaBatch(np.arange(rows) % K,
                          rng.standard_normal((rows, D)).astype(np.float32),
                          np.ones(rows, np.int64))

    sched.push(src, batch())
    sched.tick(sync=False)
    _ = sched.read_table(red)          # compile + barrier
    t0 = time.perf_counter()
    for _ in range(ticks):
        sched.push(src, batch())
        sched.tick(sync=False)
    _ = sched.read_table(red)          # barrier
    return time.perf_counter() - t0


def main():
    one, both, ratio = probe_raw_overlap()
    print(f"raw overlap: one-program {one*1e3:.1f}ms, two-device "
          f"{both*1e3:.1f}ms, ratio {ratio:.2f} "
          f"(1.0 = concurrent, 2.0 = serial)")
    w1 = probe_staged(1)
    w2 = probe_staged(2)
    print(f"staged compute shape: 1-device {w1:.3f}s, 2-device {w2:.3f}s, "
          f"speedup {w1 / w2:.2f}x")
    if ratio > 1.5:
        print("verdict: this runtime executes device programs SERIALLY "
              "across (virtual) devices — no pipeline schedule can "
              "overlap; staged parity is the expected best case.")
    else:
        print("verdict: runtime overlaps across devices — staged "
              "pipelining can win on multi-stage compute-bound graphs.")


if __name__ == "__main__":
    main()
