#!/usr/bin/env python3
"""One-shot fleet view: query a TelemetryServer or read saved state.

Usage::

    python tools/fleet_inspect.py --connect HOST:PORT         # live query
    python tools/fleet_inspect.py fleet.json                  # saved snapshot
    python tools/fleet_inspect.py --bench-dir out/            # bench JSONs
    ... --json                                                # machine form

``--connect`` dials a :class:`~reflow_tpu.obs.wire.TelemetryServer`
over TCP (or a saved ``reflow.fleet/1`` JSON file stands in for a live
aggregator) and prints the fleet: per-node lag / read QPS / link
states / epoch / staleness, the derived cross-node gauges, and the
alert lines. Exit status is 0 even when nodes are stale — staleness is
a *reported* condition, not a tool failure; ``--fail-on-alert`` makes
alerts fatal for CI smokes.

``--bench-dir`` summarizes ``bench.py --json-out`` files instead: every
``*.json`` carrying a ``reflow.bench/1`` schema stamp is listed by
mode. Pre-stamp files (older benches) are tolerated and shown as
``mode=?`` — the reader is backfill-tolerant by design.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

FLEET_SCHEMA = "reflow.fleet/1"
BENCH_SCHEMA = "reflow.bench/1"


def fetch_live(hostport: str, timeout_s: float = 2.0) -> dict:
    """Dial a TelemetryServer and fetch one fleet snapshot."""
    from reflow_tpu.net.transport import TcpTransport
    from reflow_tpu.obs.wire import TelemetryLink

    host, _, port = hostport.rpartition(":")
    link = TelemetryLink(TcpTransport(host or "127.0.0.1"),
                         (host or "127.0.0.1", int(port)),
                         node="fleet-inspect", io_timeout_s=timeout_s)
    try:
        snap = link.fetch_fleet()
    finally:
        link.close()
    if snap is None:
        raise SystemExit(f"fleet_inspect: no aggregator at {hostport} "
                         f"(link state={link.conn_state})")
    return snap


def load_snapshot(path: str) -> dict:
    with open(path) as f:
        snap = json.load(f)
    if snap.get("schema") != FLEET_SCHEMA:
        raise SystemExit(f"fleet_inspect: {path} is not a "
                         f"{FLEET_SCHEMA} snapshot "
                         f"(schema={snap.get('schema')!r})")
    return snap


def read_bench_dir(path: str) -> dict:
    """Summarize ``bench.py --json-out`` files under ``path``. Files
    without the ``reflow.bench/1`` stamp (pre-stamp benches) are kept
    with ``mode=None`` rather than rejected."""
    entries = []
    for p in sorted(glob.glob(os.path.join(path, "*.json"))):
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if not isinstance(doc, dict):
            continue
        if doc.get("schema") not in (BENCH_SCHEMA, None):
            continue  # some other tool's JSON (fleet/trace/...)
        if doc.get("schema") is None and "mode" not in doc \
                and not any(k.endswith("_per_s") or k == "results"
                            for k in doc):
            continue  # doesn't look like a bench result at all
        entries.append({"file": os.path.basename(p),
                        "schema": doc.get("schema"),
                        "mode": doc.get("mode"),
                        "keys": sorted(doc)[:12]})
    return {"schema": "reflow.fleet_benchdir/1", "dir": path,
            "benches": entries,
            "stamped": sum(1 for e in entries
                           if e["schema"] == BENCH_SCHEMA),
            "unstamped": sum(1 for e in entries if e["schema"] is None)}


def _print_fleet(snap: dict) -> None:
    g = snap.get("gauges", {})
    nodes = snap.get("nodes", {})
    print(f"fleet: {g.get('nodes_total', 0)} node(s), "
          f"{g.get('nodes_stale', 0)} stale; "
          f"{g.get('snapshots_total', 0)} snapshot(s) ingested")
    spread = g.get("lag_spread")
    qps = g.get("aggregate_read_qps")
    print(f"  lag spread: "
          f"{'n/a' if spread is None else int(spread)} tick(s)   "
          f"epochs: {g.get('epochs')} "
          f"({'agree' if g.get('epoch_agree') else 'DISAGREE'})   "
          f"read qps: {'n/a' if qps is None else qps}")
    if g.get("link_states"):
        states = ", ".join(f"{k}={v}" for k, v in
                           sorted(g["link_states"].items()))
        print(f"  links: {states}")
    debt = g.get("compact_debt_bytes")
    if debt is not None:
        print(f"  compaction debt: {int(debt)} byte(s)")
    tpeak = g.get("tile_peak_bytes")
    stiles = g.get("snapshot_tiles")
    if tpeak is not None or stiles is not None:
        print(f"  tiles: peak resident "
              f"{'n/a' if tpeak is None else int(tpeak)} byte(s)   "
              f"published snapshot tiles: "
              f"{'n/a' if stiles is None else int(stiles)}")
    if g.get("subs_active") is not None:
        rows = g.get("sub_rows_s")
        lag = g.get("sub_lag_windows")
        print(f"  subs: {int(g['subs_active'])} active   fan-out: "
              f"{'n/a' if rows is None else f'{rows:.1f}'} row/s   "
              f"slowest lag: "
              f"{'n/a' if lag is None else int(lag)} window(s)")
    f50 = g.get("subs.freshness_p50")
    f99 = g.get("subs.freshness_p99")
    fev = g.get("flight.events_total")
    if f50 is not None or f99 is not None or fev is not None:
        print(f"  freshness: p50 "
              f"{'n/a' if f50 is None else f'{f50 * 1e3:.1f}ms'}   p99 "
              f"{'n/a' if f99 is None else f'{f99 * 1e3:.1f}ms'}   "
              f"flight events: {'n/a' if fev is None else int(fev)}")
    hdr = (f"  {'node':<16} {'horizon':>8} {'lag':>5} {'qps':>8} "
           f"{'epoch':>6} {'age_s':>7}  state")
    print(hdr)
    for name, e in sorted(nodes.items()):
        conn = ",".join(sorted(set(e.get("conn_states", {}).values()))) \
            or "-"
        if e.get("stale"):
            conn += " STALE"
        qps = e.get("read_qps")
        print(f"  {name:<16} "
              f"{e.get('horizon') if e.get('horizon') is not None else '-':>8} "
              f"{e.get('lag_ticks') if e.get('lag_ticks') is not None else '-':>5} "
              f"{f'{qps:.1f}' if qps is not None else '-':>8} "
              f"{int(e['epoch']) if e.get('epoch') is not None else '-':>6} "
              f"{e.get('age_s', 0):>7.2f}  {conn}")
    for line in snap.get("alerts", []):
        print(f"  ALERT: {line}")


def _print_benchdir(summary: dict) -> None:
    print(f"{summary['dir']}: {len(summary['benches'])} bench file(s) "
          f"({summary['stamped']} stamped, "
          f"{summary['unstamped']} pre-stamp)")
    for e in summary["benches"]:
        mode = e["mode"] if e["mode"] is not None else "?"
        print(f"  {e['file']:<32} mode={mode}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("snapshot", nargs="?",
                    help="saved reflow.fleet/1 JSON file")
    ap.add_argument("--connect", metavar="HOST:PORT",
                    help="dial a live TelemetryServer instead")
    ap.add_argument("--bench-dir", metavar="DIR",
                    help="summarize bench.py --json-out files instead")
    ap.add_argument("--json", action="store_true",
                    help="print the summary as one JSON line")
    ap.add_argument("--fail-on-alert", action="store_true",
                    help="exit 1 when the fleet has any alert line")
    args = ap.parse_args(argv)
    if args.bench_dir:
        summary = read_bench_dir(args.bench_dir)
        if args.json:
            print(json.dumps(summary))
        else:
            _print_benchdir(summary)
        return 0
    if args.connect:
        snap = fetch_live(args.connect)
    elif args.snapshot:
        snap = load_snapshot(args.snapshot)
    else:
        ap.error("need a snapshot file, --connect, or --bench-dir")
        return 2
    if args.json:
        print(json.dumps(snap))
    else:
        _print_fleet(snap)
    if args.fail_on_alert and snap.get("alerts"):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
