#!/usr/bin/env python3
"""reflow-top: live terminal view of the replicated tier's fleet.

Usage::

    python tools/reflow_top.py --connect HOST:PORT          # live, 1s refresh
    python tools/reflow_top.py --connect HOST:PORT --once   # one frame
    python tools/reflow_top.py fleet.json --once            # saved snapshot

Each refresh fetches one ``reflow.fleet/1`` snapshot from the
:class:`~reflow_tpu.obs.wire.TelemetryServer` and redraws: one row per
node (replication horizon, lag ticks, read QPS, epoch, link states,
snapshot age), the fleet gauges line (lag spread, epoch agreement,
aggregate QPS, compaction debt), brownout levels where a node reports
them, and the aggregator's alert lines. A node whose telemetry went
quiet is shown ``STALE`` with its age — the fleet view keeps serving
last-known state through a telemetry partition, and so does this
console: when a fetch fails it redraws the previous frame marked
``[disconnected]`` instead of exiting. Ctrl-C quits.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CLEAR = "\x1b[2J\x1b[H"


def render(snap: dict, *, stale_link: bool = False) -> str:
    """One frame of the console as a string (testable without a TTY)."""
    g = snap.get("gauges", {})
    nodes = snap.get("nodes", {})
    lines = []
    flag = " [disconnected]" if stale_link else ""
    lines.append(f"reflow-top — {g.get('nodes_total', 0)} node(s), "
                 f"{g.get('nodes_stale', 0)} stale, "
                 f"{g.get('snapshots_total', 0)} snapshot(s){flag}")
    spread = g.get("lag_spread")
    qps = g.get("aggregate_read_qps")
    debt = g.get("compact_debt_bytes")
    lines.append(
        f"lag spread {('n/a' if spread is None else int(spread))} "
        f"tick(s) | epochs {g.get('epochs')} "
        f"{'agree' if g.get('epoch_agree') else 'DISAGREE'} | "
        f"read qps {('n/a' if qps is None else qps)} | "
        f"compact debt {('n/a' if debt is None else int(debt))} B")
    if g.get("subs_active") is not None:
        srows = g.get("sub_rows_s")
        slag = g.get("sub_lag_windows")
        f50 = g.get("subs.freshness_p50")
        f99 = g.get("subs.freshness_p99")
        fev = g.get("flight.events_total")
        lines.append(
            f"subs {int(g['subs_active'])} active | fan-out "
            f"{('n/a' if srows is None else f'{srows:.1f}')} row/s | "
            f"slowest lag "
            f"{('n/a' if slag is None else int(slag))} window(s) | "
            f"fresh p50 "
            f"{('n/a' if f50 is None else f'{f50 * 1e3:.1f}ms')} p99 "
            f"{('n/a' if f99 is None else f'{f99 * 1e3:.1f}ms')} | "
            f"flight "
            f"{('n/a' if fev is None else int(fev))}")
    lines.append(f"{'NODE':<16} {'HORIZON':>8} {'LAG':>5} {'QPS':>8} "
                 f"{'EPOCH':>6} {'AGE':>7} LINKS")
    for name, e in sorted(nodes.items()):
        states = e.get("conn_states", {})
        conn = ",".join(f"{k.rsplit('.', 2)[-2]}={v}"
                        for k, v in sorted(states.items())) or "-"
        if e.get("stale"):
            conn = f"STALE({e.get('age_s', 0):.1f}s) {conn}"
        nqps = e.get("read_qps")
        hor = e.get("horizon")
        lag = e.get("lag_ticks")
        ep = e.get("epoch")
        lines.append(
            f"{name:<16} "
            f"{int(hor) if hor is not None else '-':>8} "
            f"{int(lag) if lag is not None else '-':>5} "
            f"{f'{nqps:.1f}' if nqps is not None else '-':>8} "
            f"{int(ep) if ep is not None else '-':>6} "
            f"{e.get('age_s', 0):>6.1f}s {conn}")
        brown = e.get("brownout")
        if brown:
            levels = ", ".join(f"{k}={v}" for k, v in sorted(brown.items()))
            lines.append(f"{'':<16} brownout: {levels}")
        if e.get("subs_active") is not None:
            srows = e.get("sub_rows_s")
            slag = e.get("sub_lag_windows")
            sconf = e.get("sub_conflations")
            nf50 = e.get("sub_freshness_p50")
            lines.append(
                f"{'':<16} subs: {int(e['subs_active'])} active, "
                f"{('n/a' if srows is None else f'{srows:.1f}')} row/s, "
                f"conflated "
                f"{('n/a' if sconf is None else int(sconf))}, "
                f"lag "
                f"{('n/a' if slag is None else int(slag))} window(s), "
                f"fresh p50 "
                f"{('n/a' if nf50 is None else f'{nf50 * 1e3:.1f}ms')}")
        fev = e.get("flight_events")
        if fev is not None:
            lines.append(f"{'':<16} flight: {int(fev)} event(s) recorded")
    for line in snap.get("alerts", []):
        lines.append(f"ALERT: {line}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("snapshot", nargs="?",
                    help="saved reflow.fleet/1 JSON (for --once)")
    ap.add_argument("--connect", metavar="HOST:PORT",
                    help="dial a live TelemetryServer")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="refresh period in seconds (default 1.0)")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no clear-screen)")
    args = ap.parse_args(argv)
    if not args.connect and not args.snapshot:
        ap.error("need --connect or a snapshot file")
        return 2

    link = None
    if args.connect:
        from reflow_tpu.net.transport import TcpTransport
        from reflow_tpu.obs.wire import TelemetryLink
        host, _, port = args.connect.rpartition(":")
        host = host or "127.0.0.1"
        link = TelemetryLink(TcpTransport(host), (host, int(port)),
                             node="reflow-top", io_timeout_s=2.0)

    def fetch():
        if link is not None:
            return link.fetch_fleet()
        with open(args.snapshot) as f:
            return json.load(f)

    last = None
    try:
        while True:
            snap = fetch()
            stale_link = snap is None
            if snap is None:
                snap = last
            if snap is None:
                print("reflow-top: aggregator unreachable, retrying...",
                      file=sys.stderr)
            else:
                last = snap
                frame = render(snap, stale_link=stale_link)
                if args.once:
                    print(frame)
                    return 0
                sys.stdout.write(_CLEAR + frame + "\n")
                sys.stdout.flush()
            if args.once:
                return 1  # --once with nothing to render
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if link is not None:
            link.close()


if __name__ == "__main__":
    sys.exit(main())
