#!/usr/bin/env python3
"""reflow-lint: the project's invariant checker.

Usage::

    python tools/reflow_lint.py                  # all fast passes
    python tools/reflow_lint.py --json           # reflow.lint/1 report
    python tools/reflow_lint.py --passes locks,seams
    python tools/reflow_lint.py --rules bare-assert
    python tools/reflow_lint.py --hlo            # + slow HLO audit
    python tools/reflow_lint.py --list-rules

Exit codes: 0 clean, 1 findings, 2 usage/internal error. Waive a
finding inline with a reason::

    # reflow-lint: waive <rule> -- <why this is safe>

See docs/guide.md "Static analysis & lockcheck" for the rule catalog.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        prog="reflow_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--root", default=None,
                    help="repo root to lint (default: the repo this "
                         "script lives in)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the reflow.lint/1 JSON report")
    ap.add_argument("--passes", default=None,
                    help="comma-separated pass subset (default: all)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule filter (default: all)")
    ap.add_argument("--hlo", action="store_true",
                    help="also run the slow HLO constant audit "
                         "(executes workloads; tens of seconds each)")
    ap.add_argument("--hlo-workloads", default=None,
                    help="workload subset for --hlo")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args()

    from reflow_tpu.analysis import core, run
    from reflow_tpu.analysis import constants as hlo

    if args.list_rules:
        # import the passes so every rule is registered
        from reflow_tpu.analysis import (envknobs, exceptions,  # noqa: F401
                                         locks, metrics_pass, seams)
        for name in sorted(core.RULES):
            print(f"{name:28s} {core.RULES[name]}")
        return 0

    root = args.root or os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    passes = args.passes.split(",") if args.passes else None
    rules = args.rules.split(",") if args.rules else None
    try:
        report = run(root, passes=passes, rules=rules)
    except KeyError as e:
        print(f"reflow_lint: {e}", file=sys.stderr)
        return 2

    if args.hlo:
        wl = args.hlo_workloads.split(",") if args.hlo_workloads else None
        extra = hlo.hlo_pass(root, wl)
        report["findings"].extend(f.to_dict() for f in extra)
        for f in extra:
            report["counts"][f.rule] = report["counts"].get(f.rule, 0) + 1
        report["passes"] = list(report["passes"]) + ["hlo"]

    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        print(core.render_report(report))
    return 1 if report["findings"] else 0


if __name__ == "__main__":
    sys.exit(main())
