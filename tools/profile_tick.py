"""Attribute the PageRank churn tick's wall time (VERDICT r2 #8 follow-up).

The linear-fixpoint tick program is one fused jit; its phases are closures
(executors/linear_fixpoint.py), so this tool attributes cost empirically
on the real chip:

  T_zero   K zero-churn ticks in ONE device execution (tick_many): the
           churn batch carries only weight-0 rows, so phase A runs, the
           CSR cache validates (no appends -> the tail build is skipped),
           and the while_loop quiesces after its first predicate — i.e.
           the tick's FIXED cost.
  T_churn  K real churn ticks in one execution: fixed cost + the loop
           passes. (T_churn - T_zero) / passes = per-pass cost.
  T_csr    the full CSR REBUILD (argsort + scatter-count/cumsum bounds —
           since round 4 paid only on compaction/tail-overflow ticks, not
           per tick) reconstructed standalone and scanned K times in one
           execution; the obsolete searchsorted form alongside.

Timing protocol: everything is measured AFTER the process's first
readback, i.e. in the tunnel's degraded-synchronous mode where a single
long execution runs at true device speed (measured by bench.py's
full-recompute rounds); K-fold fusion amortizes the ~0.1s per-execution
sync overhead below the noise floor.

Usage:  python tools/profile_tick.py            # full scale, real chip
        REFLOW_BENCH_SMOKE=1 python tools/profile_tick.py   # tiny, CPU ok
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

from reflow_tpu.utils.config import env_flag

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from bench import _build_pagerank
    from bench_configs import _sync_read, _timed_tick
    from reflow_tpu.delta import DeltaBatch
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import pagerank

    smoke = env_flag("REFLOW_BENCH_SMOKE")
    n_nodes = 1_000 if smoke else 100_000
    n_edges = 10_000 if smoke else 1_000_000
    churn = 0.01
    K = 4 if smoke else 8

    pr, web = _build_pagerank(n_nodes, n_edges, churn, 1e-4)
    ex = get_executor("tpu")
    sched = DirtyScheduler(pr.graph, ex)
    sched.push(pr.teleport, pagerank.teleport_batch(n_nodes))
    sched.push(pr.edges, web.initial_batch())
    sched.tick(sync=False)

    # absorb the churn-shape compile; land in the degraded-sync regime
    # deliberately (one readback), so every window below is device-bound
    sched.push(pr.edges, web.churn(churn))
    _timed_tick(sched)

    # churn batches are retract+insert pairs over m rewired edges; size
    # the zero batch the same WITHOUT calling churn() (churn mutates the
    # host WebGraph, and a discarded batch would desync host vs device)
    cap = 2 * max(1, int(n_edges * churn))

    def zero_batch():
        return DeltaBatch(np.zeros(cap, np.int64),
                          np.zeros((cap, 2), np.float32),
                          np.zeros(cap, np.int64))

    def window(feeds, tag):
        t0 = time.perf_counter()
        agg = sched.tick_many(feeds)
        _sync_read(ex)
        wall = time.perf_counter() - t0
        agg.block()
        log(f"{tag}: {wall:.3f}s for {len(feeds)} ticks "
            f"({wall / len(feeds) * 1e3:.1f} ms/tick, passes={agg.passes})")
        return wall / len(feeds), agg.passes

    # macro-tick compile absorption for both shapes
    window([{pr.edges: zero_batch()} for _ in range(K)], "warm zero")
    window([{pr.edges: web.churn(churn)} for _ in range(K)], "warm churn")

    t_zero, _ = window([{pr.edges: zero_batch()} for _ in range(K)],
                       "zero-churn (fixed+CSR)")
    t_churn, passes = window([{pr.edges: web.churn(churn)}
                              for _ in range(K)], "churn")
    loop_passes = max(1, (passes - 2 * K) / K)  # minus phase A + exit per tick

    # standalone CSR rebuild at the real arena shape
    jst = ex.states[pr.join.id]
    Rcap = jst["rkeys"].shape[0]
    Klc = pr.join.inputs[0].spec.key_space
    log(f"arena capacity {Rcap}, key space {Klc}")

    def time_scanned(name, once):
        """Scan ``once`` K times in one execution; true completion wall
        via a readback (block_until_ready does NOT wait over the tunnel,
        so the warm call drains with a readback too)."""
        fn = jax.jit(lambda rk, rw: jax.lax.scan(
            once, (rk, rw), (), length=K)[0])
        r = fn(jst["rkeys"], jst["rw"])
        np.asarray(r[0][0])                     # drain compile + warm run
        t0 = time.perf_counter()
        r = fn(jst["rkeys"], jst["rw"])
        np.asarray(r[0][0])
        per = (time.perf_counter() - t0) / K
        log(f"{name}: {per * 1e3:.1f} ms")
        return per

    def use_order(rw, order):
        """Position-weighted sum: irreducibly consumes the FULL permutation
        (folding only order[0]/order[-1] lets XLA collapse the argsort
        into a min/max reduction and the timing lies)."""
        iota = jnp.arange(order.shape[0], dtype=jnp.int32)
        return jnp.sum(rw[order] * iota)

    def sort_only(c, _):
        rk, rw = c
        skey = jnp.where(rw != 0, rk, Klc)
        order = jnp.argsort(skey)
        return (rk ^ use_order(rw, order), rw ^ order[0]), ()

    def full_csr(c, _):
        rk, rw = c
        skey = jnp.where(rw != 0, rk, Klc)
        order = jnp.argsort(skey)
        sk = skey[order]
        bounds = jnp.searchsorted(
            sk, jnp.arange(Klc + 1, dtype=jnp.int32)).astype(jnp.int32)
        return (rk ^ bounds[0] ^ use_order(rw, order), rw ^ order[0]), ()

    def counts_csr(c, _):
        # searchsorted-free bounds: scatter-count + cumsum (the form
        # linear_fixpoint.py builds)
        rk, rw = c
        skey = jnp.where(rw != 0, rk, Klc)
        order = jnp.argsort(skey)
        deg = jnp.zeros((Klc + 1,), jnp.int32).at[skey].add(
            1, mode="drop")[:Klc]
        bounds = jnp.cumsum(deg) - deg
        return (rk ^ bounds[0] ^ use_order(rw, order), rw ^ order[0]), ()

    t_sort = time_scanned("argsort only", sort_only)
    time_scanned("CSR via searchsorted (obsolete form)", full_csr)
    # counts/cumsum is the rebuild-path form linear_fixpoint.py builds
    t_csr = time_scanned("CSR (argsort + counts/cumsum)", counts_csr)

    per_pass = (t_churn - t_zero) / loop_passes
    print(f"fixed         {t_zero * 1e3:8.1f} ms/tick")
    print(f"  CSR rebuild {t_csr * 1e3:8.1f} ms (argsort {t_sort * 1e3:.1f};"
          f" amortized over ticks between compactions)")
    print(f"loop          {(t_churn - t_zero) * 1e3:8.1f} ms/tick "
          f"({loop_passes:.1f} passes x {per_pass * 1e3:.1f} ms)")
    print(f"total         {t_churn * 1e3:8.1f} ms/tick")


if __name__ == "__main__":
    main()
