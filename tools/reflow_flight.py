#!/usr/bin/env python3
"""Merge the fleet's flight recorders into one post-mortem timeline.

Usage::

    python tools/reflow_flight.py ROOT            # scan ROOT/**/flight/
    python tools/reflow_flight.py DIR1 DIR2 ...   # explicit corners
    ... --json                                    # machine form
    ... --last 50                                 # tail of the timeline

Each process's :class:`~reflow_tpu.obs.flight.FlightRecorder` writes a
bounded JSONL ring under its own state directory (``<root>/<node>/
flight/``); every file header carries a ``{mono, wall}`` clock anchor
taken when the file was opened. The merger maps each event's
process-local monotonic timestamp onto the wall clock through its
file's anchor (``wall = anchor.wall + (mono - anchor.mono)``) and
sorts the union — one fleet-wide timeline that still works when some
of the processes were kill -9'd mid-write (torn final lines are
dropped by the reader; a respawned node's dead incarnation survives as
the ``.prev`` generation).

Wall-clock caveat: all the chaos topologies run on one host, where
``CLOCK_MONOTONIC`` is shared and the anchors differ only by file-open
time — orderings across processes are honest. Across *hosts* the
anchors inherit NTP skew; the timeline is for operator forensics, not
for ordering proofs (those ride the causality tokens in the spans
themselves).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reflow_tpu.obs.flight import read_flight_dir  # noqa: E402

MERGED_SCHEMA = "reflow.flight_merged/1"


def find_corners(paths) -> list:
    """Flight directories under the given roots: a path that *is* a
    corner (contains flight-*.jsonl) is taken as-is; otherwise its
    tree is scanned for ``flight/`` directories."""
    corners = []
    for p in paths:
        if not os.path.isdir(p):
            continue
        if any(fn.startswith("flight-") and fn.endswith((".jsonl",
                                                         ".jsonl.prev"))
               for fn in os.listdir(p)):
            corners.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            if os.path.basename(dirpath) == "flight" and any(
                    fn.startswith("flight-") for fn in filenames):
                corners.append(dirpath)
                dirnames[:] = []
    return sorted(set(corners))


def merge(paths) -> dict:
    """Read every corner under ``paths`` and merge into the
    ``reflow.flight_merged/1`` report: clock-anchored events sorted on
    the reconstructed wall axis, plus per-node file accounting."""
    corners = find_corners(paths)
    events = []
    nodes: dict = {}
    for corner in corners:
        for parsed in read_flight_dir(corner):
            hdr = parsed["header"]
            node = hdr.get("node", "?")
            anchor = hdr.get("anchor", {})
            a_mono = float(anchor.get("mono", 0.0))
            a_wall = float(anchor.get("wall", 0.0))
            entry = nodes.setdefault(node, {
                "files": 0, "events": 0, "pids": [], "corner": corner})
            entry["files"] += 1
            entry["events"] += len(parsed["events"])
            pid = hdr.get("pid")
            if pid is not None and pid not in entry["pids"]:
                entry["pids"].append(pid)
            for ev in parsed["events"]:
                mono = float(ev.get("mono", 0.0))
                events.append({
                    "t_wall": a_wall + (mono - a_mono),
                    "node": node,
                    "pid": pid,
                    "kind": ev.get("kind", "span"),
                    "name": ev.get("name", "?"),
                    "dur": ev.get("dur", 0.0),
                    "track": ev.get("track"),
                    "args": ev.get("args"),
                })
    events.sort(key=lambda e: (e["t_wall"], e["node"], e["name"]))
    return {"schema": MERGED_SCHEMA, "corners": corners,
            "nodes": nodes, "events": events}


def _print_human(report: dict, last: int) -> None:
    nodes = report["nodes"]
    print(f"{len(nodes)} node(s), "
          f"{sum(n['events'] for n in nodes.values())} event(s) across "
          f"{sum(n['files'] for n in nodes.values())} flight file(s)")
    for name, n in sorted(nodes.items()):
        print(f"  {name:<16} {n['events']:>6} event(s) in "
              f"{n['files']} file(s)  pids={n['pids']}  {n['corner']}")
    events = report["events"]
    if not events:
        return
    base = events[0]["t_wall"]
    shown = events[-last:] if last else events
    if len(shown) < len(events):
        print(f"  ... ({len(events) - len(shown)} earlier event(s))")
    for ev in shown:
        args = ev.get("args") or {}
        cause = args.get("cause") or ""
        extra = f" cause={cause}" if cause else ""
        if "causes" in args:
            extra += f" causes={len(args['causes'])}"
        print(f"  +{ev['t_wall'] - base:10.4f}s {ev['node']:<12} "
              f"{ev['kind']:<5} {ev['name']:<18} "
              f"{1e3 * float(ev.get('dur') or 0.0):8.3f}ms{extra}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="fleet root(s) or explicit flight corner(s)")
    ap.add_argument("--json", action="store_true",
                    help="print the merged timeline as one JSON line")
    ap.add_argument("--last", type=int, default=0, metavar="N",
                    help="human mode: show only the last N events")
    args = ap.parse_args(argv)
    report = merge(args.paths)
    if args.json:
        print(json.dumps(report))
    else:
        _print_human(report, args.last)
    if not report["nodes"]:
        print("reflow_flight: no flight recordings found under "
              f"{args.paths}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
