#!/usr/bin/env python3
"""Checkout-relative wrapper for ``python -m reflow_tpu.proc``.

Usage::

    python tools/reflow_proc.py --role leader   --name leader --root DIR
    python tools/reflow_proc.py --role replica  --name r0 --root DIR \\
        --telemetry HOST:PORT
    python tools/reflow_proc.py --role producer --name p0 --index 0 \\
        --connect HOST:PORT --json

Runs one multi-process deployment role (docs/guide.md "Multi-process
deployment"): a leader (durable scheduler + ingestion RPC + WAL
shipper), a replica (mirrored WAL + shipping/control endpoint), or a
producer (deterministic batch stream over the ingestion RPC). The
first stdout line is the ready JSON with the OS-assigned addresses;
``--json`` adds an exit-status JSON on clean shutdown. The process
harness spawns children through the ``-m`` form; this wrapper exists
so an operator inside a checkout gets the identical entrypoint without
installing the package.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reflow_tpu.proc.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
