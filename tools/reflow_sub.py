#!/usr/bin/env python3
"""Checkout-relative wrapper for ``python -m reflow_tpu.subs``.

Usage::

    python tools/reflow_sub.py --connect HOST:PORT --sink counts \\
        --kind topk --k 5
    python tools/reflow_sub.py --connect HOST:PORT --sink counts \\
        --kind lookup --key the,2 --json

Tails one standing query against a replica's subscription endpoint
(docs/guide.md "Reactive reads"): one line per applied commit window,
human by default, ``reflow.sub/1`` JSON documents with ``--json``.
The wrapper exists so an operator inside a checkout gets the
identical entrypoint without installing the package.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from reflow_tpu.subs.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
