#!/usr/bin/env bash
# The tier-1 verify gate, verbatim from ROADMAP.md — CI and humans run
# the IDENTICAL command (CPU-forced jax, `slow`-marked tests excluded,
# collection errors tolerated so one broken module can't hide the rest).
# Prints DOTS_PASSED=<n> (count of passing-test dots) and exits with
# pytest's status.
set -o pipefail
cd "$(dirname "$0")/.."

# fast-fail static pass BEFORE the 15-minute pytest budget: a syntax
# error or obvious undefined name should cost seconds, not a timeout.
# pyflakes is optional in the image; compileall is stdlib.
python -m compileall -q reflow_tpu tests tools bench.py bench_configs.py \
  || { echo "TIER1: compileall failed"; exit 2; }
if python -c "import pyflakes" 2>/dev/null; then
  python -m pyflakes reflow_tpu bench.py bench_configs.py \
    || { echo "TIER1: pyflakes failed"; exit 2; }
fi
# reflow-lint: the project's own invariant checker (lock discipline,
# seam hygiene, metrics pairing, env-knob registry, exception policy).
# AST-only — seconds, no jax import. docs/guide.md has the rule catalog.
python tools/reflow_lint.py \
  || { echo "TIER1: reflow-lint found violations"; exit 2; }

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
dots=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
echo DOTS_PASSED=$dots

# regression floor: the suite passed 570 at the PR-20 baseline (533 at
# PR 18, 395 at PR 11, 380 at PR 10, 333 at PR 8, 315 at PR 6); a run
# below the previous baseline means previously-green tests broke (or
# silently vanished), even if pytest's own exit status reads clean.
FLOOR=${TIER1_FLOOR:-560}
if [ "$dots" -lt "$FLOOR" ]; then
  echo "TIER1: DOTS_PASSED=$dots below floor $FLOOR"
  rc=4
fi

# optional (RUN_BENCH=1): the lockcheck smoke — re-run the concurrent
# suites (serve/tier/failover: producers, pump pools, shippers,
# failover coordinator) with the runtime lock-order monitor armed.
# Every named_lock acquisition feeds the held-before graph; ANY cycle
# raises LockOrderError and fails the run. The static twin is the
# reflow-lint lock pass above; this leg catches the orders the AST
# can't see (callbacks, cross-module call chains).
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_LOCKCHECK=1 JAX_PLATFORMS=cpu timeout -k 10 600 \
    python -m pytest tests/test_serve.py tests/test_tier.py \
    tests/test_failover.py -q -m 'not slow' -p no:cacheprovider \
    || { echo "TIER1: lockcheck smoke failed"; rc=3; }
fi

# optional (RUN_BENCH=1): the serve-mode smoke — sustained ingestion
# throughput must coalesce (>1 micro-batch/tick at 16 producers) with
# zero forced syncs; ~seconds on CPU at smoke scale.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_SERVE=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py > /tmp/_t1_serve.json || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_serve.json"))
assert r["coalesce_gt_1_at_16p"], r
assert r["zero_forced_syncs"], r
print(f"TIER1 serve smoke: {r['serve_16p_rows_per_s']} rows/s @16p, "
      f"coalesce {r['serve_16p_coalesce_factor']}x")
EOF
fi

# optional (RUN_BENCH=1): the tier-mode smoke — 4 graphs x 4 producers
# on a 2-thread pump pool: zero forced syncs, pump-crash isolation with
# exactly-once recovery, and a bounded quiet-tenant admission p99.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_TIER=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py > /tmp/_t1_tier.json || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_tier.json"))
assert r["zero_forced_syncs"], r
assert r["crash_exactly_once"], r
assert r["quiet_p99_bounded"], r
print(f"TIER1 tier smoke: {r['tier_rows_per_s_4g_2threads']} rows/s "
      f"(4g, 2 threads), crash isolation ok, quiet p99 "
      f"{r['quiet_admission_p99_us']}us")
EOF
fi

# optional (RUN_BENCH=1): the control-mode smoke — the self-healing
# control plane under step load: during a hot-tenant surge only the
# surging graph is browned out (the quiet tenant's admission p99 stays
# bounded), the tier returns to its configured policies within the
# analytic bound of control intervals after the surge ends, and a
# pump-crash storm trips the circuit breaker then heals through
# half-open with no manual intervention.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_CONTROL=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py > /tmp/_t1_control.json || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_control.json"))
assert r["quiet_p99_bounded"], r
assert r["only_hot_degraded"], r
assert r["recovered_within_bound"], r
assert r["breaker_opened"], r
assert r["breaker_recovered"], r
assert r["sibling_applied_during_storm"], r
assert r["post_recovery_applied"], r
print(f"TIER1 control smoke: quiet p99 {r['quiet_admission_p99_us']}us "
      f"during surge, recovered in {r['recovery_ticks']} ticks "
      f"(bound {r['recovery_bound_ticks']}), breaker open->closed in "
      f"{r['breaker_heal_s']}s")
EOF
fi

# optional (RUN_BENCH=1): the obs-mode smoke — tracing + telemetry on
# the 16-producer serve protocol: the exported chrome trace must be
# valid JSON with span events, and every sampled ticket's stage
# durations must sum to within 10% of its end-to-end latency.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_OBS=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    REFLOW_TRACE_OUT=/tmp/_t1_obs_trace.json \
    timeout -k 10 300 python bench.py > /tmp/_t1_obs.json || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_obs.json"))
assert r["decomposition_ok"], r
assert r["snapshot_schema_ok"], r
t = json.load(open(r["trace_file"]))  # must parse as chrome trace JSON
evs = [e for e in t["traceEvents"] if e.get("ph") == "X"]
assert evs and all("ts" in e and "dur" in e and "tid" in e for e in evs), \
    "trace events malformed"
print(f"TIER1 obs smoke: {r['sampled_tickets']} tickets decomposed "
      f"(max dev {100 * r['decomposition_max_dev_frac']:.2f}%), "
      f"{len(evs)} trace spans, overhead "
      f"{100 * r['obs_overhead_frac']:.2f}%")
EOF
fi

# optional (RUN_BENCH=1): the walpipe-mode smoke — the asynchronous
# durability pipeline: device-resident pre-imaged submissions under
# fsync="record" must log with ZERO forced materialize readbacks, and
# the pipelined committer must not be slower than the inline one.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_WALPIPE=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py > /tmp/_t1_walpipe.json || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_walpipe.json"))
assert r["zero_materialize_readbacks"], r
assert r["pipelined_ge_inline"], r
assert r["replay_view_matches"], r
print(f"TIER1 walpipe smoke: {r['walpipe_speedup_16p']}x pipelined vs "
      f"inline @16p, 0 log readbacks, replay ok")
EOF
fi

# optional (RUN_BENCH=1): the mega-tick smoke — the compiled window
# path must engage (no fallbacks), produce views identical to the
# per-tick twin, and keep the amortized per-tick wall within a generous
# CI bound of the window's dispatch wall (the acceptance target is 2x
# on device; CPU-backed CI gets slack for scheduling noise).
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_MEGATICK=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py > /tmp/_t1_megatick.json || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_megatick.json"))
assert r["views_match"], r
assert r["megatick_fallbacks"] == 0, r
assert r["amortized_over_dispatch_x"] < 25, r
print(f"TIER1 megatick smoke: tick_s_amortized {r['tick_s_amortized']}s "
      f"vs window_dispatch_s {r['window_dispatch_s']}s "
      f"({r['amortized_over_dispatch_x']}x), "
      f"{r['megatick_windows']} fused windows, views match")
EOF
fi

# optional (RUN_BENCH=1): the pipeline smoke — pipelined window
# execution: depth 2 must produce tables EXACTLY equal to depth 1 (same
# fused program, same slots, same order — bitwise), never fall back to
# per-tick, genuinely overlap host staging with in-flight dispatch
# (stage_overlap_frac > 0), and pay no amortized-tick throughput tax.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_PIPELINE=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py > /tmp/_t1_pipeline.json || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_pipeline.json"))
assert r["views_match"] and r["max_abs_diff"] == 0.0, r
assert r["twin_views_match"], r
assert r["zero_fallbacks"], r
assert r["overlap_at_depth2"], r
assert r["depth2_not_slower"], r
print(f"TIER1 pipeline smoke: depth2 {r['depth2_tick_s_amortized']}s/tick "
      f"vs depth1 {r['depth1_tick_s_amortized']}s/tick "
      f"({r['depth2_vs_depth1_x']}x), overlap "
      f"{100 * r['depth2_stage_overlap_frac']:.0f}%, parity exact")
EOF
fi

# optional (RUN_BENCH=1): the shardserve smoke — pod-scale serving
# under 8 forced host devices: spread tenants must land on distinct
# devices and share window programs (cache hits), the sharded hot
# tenant must run fused windows across the mesh, views must match the
# CPU oracle EXACTLY, and no config may fall back. The >=-baseline
# rows/s acceptance holds on real multi-chip hardware; forced host
# devices share the CI cores, so here the flags carry the bench's
# documented cpu slack and the smoke asserts them plus exactness.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_SHARDSERVE=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 590 python bench.py --json-out /tmp/_t1_shardserve.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_shardserve.json"))
assert r["views_match"], r
assert r["spread_max_abs_diff"] == 0.0, r
assert r["sharded_max_abs_diff"] == 0.0, r
assert r["spread_fallbacks"] == 0 and r["sharded_fallbacks"] == 0, r
assert r["spread_devices_distinct"], r
assert r["spread_cache_hits"] > 0, r
assert r["spread_ge_baseline"] and r["sharded_ge_baseline"], r
print(f"TIER1 shardserve smoke: spread {r['spread_rows_per_s']} rows/s "
      f"on {len(r['spread_devices'])} devices "
      f"({r['spread_cache_hits']} shared-program hits), sharded "
      f"{r['sharded_rows_per_s']} rows/s on {r['sharded_device']}, "
      f"views exact")
EOF
fi

# optional (RUN_BENCH=1): the replica smoke — WAL shipping + read
# replicas under sustained 16-producer writes: leader-vs-replica views
# at the same horizon must match EXACTLY, replica lag must settle
# within one commit window after quiesce, and aggregate replica read
# QPS must beat the serialized leader baseline. The acceptance target
# is >=2x with 4 replicas; CI cores are shared between producers,
# shipper, replayers, and readers, so the smoke gate takes the bench's
# documented CPU slack (>=1.5x) and asserts exactness + lag unchanged.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_REPLICA=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py --json-out /tmp/_t1_replica.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_replica.json"))
assert r["parity_max_abs_diff"] == 0.0, r
assert r["lag_bound_ok"], r
assert r["ship_nacks"] == 0, r
assert r["read_scaling_x"] >= 1.5, r
print(f"TIER1 replica smoke: {r['replicas']} replicas "
      f"{r['replica_read_qps']} reads/s vs leader "
      f"{r['leader_read_qps']} reads/s ({r['read_scaling_x']}x), "
      f"parity exact, final lag {r['final_lag_ticks']} ticks "
      f"(bound {r['window_ticks']})")
EOF
fi

# optional (RUN_BENCH=1): the failover smoke — kill the leader under
# sustained 16-producer writes: the FailoverCoordinator must detect,
# fence, elect and promote within a bounded wall; zero acked-write loss
# (final view == a fold of every acked batch, exactly once); the new
# leader's view at the promotion horizon must equal the winner-
# replica's published view EXACTLY; the zombie's appends rejected.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_FAILOVER=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py --json-out /tmp/_t1_failover.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_failover.json"))
assert r["acked_loss_max_abs_diff"] == 0, r
assert r["promotion_parity_max_abs_diff"] == 0, r
assert r["fence_rejected_appends"] >= 1, r
assert r["epoch"] == 1, r
assert r["detection_s"] + r["promotion_s"] + r["first_window_s"] < 30, r
print(f"TIER1 failover smoke: {r['winner']} promoted to epoch "
      f"{r['epoch']} — detect {r['detection_s']}s, promote "
      f"{r['promotion_s']}s, first window {r['first_window_s']}s; "
      f"{r['acked_batches']} acked batches, zero loss, parity exact")
EOF
fi

# optional (RUN_BENCH=1): the chaos smoke — WAL shipping over real TCP
# links through the seeded fault injector (drop/dup/reorder/corrupt/
# delay + a scripted one-way partition and connection reset), then
# quiesce and a leader kill: zero acked-write loss, exact view parity
# at equal horizons, lag <= one commit window after faults stop, and
# every post-fence shipment from the ex-leader NACKed, never ACKed.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_CHAOS=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py --json-out /tmp/_t1_chaos.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_chaos.json"))
assert r["acked_loss_max_abs_diff"] == 0, r
assert r["parity_max_abs_diff"] == 0, r
assert r["promotion_parity_max_abs_diff"] == 0, r
assert r["lag_after_quiesce_ticks"] <= r["window_ticks"], r
assert r["ex_leader_fence_nacks"] >= 1, r
assert r["ex_leader_post_fence_acks"] == 0, r
assert r["reconnects_total"] >= 1, r
assert r["retransmit_bytes"] > 0, r
print(f"TIER1 chaos smoke: {r['acked_batches']} acked batches, zero "
      f"loss, parity exact at equal horizons; converged "
      f"{r['converge_s']}s after quiesce (lag "
      f"{r['lag_after_quiesce_ticks']} <= {r['window_ticks']}); "
      f"{r['reconnects_total']} reconnect(s), "
      f"{r['retransmit_bytes']} retransmit byte(s); ex-leader fenced "
      f"({r['ex_leader_fence_nacks']} NACK(s), 0 ACKs)")
EOF
fi

# optional (RUN_BENCH=1): the bounded-history smoke — two identically-
# fed 16-producer legs (unbounded oracle vs incremental checkpoint
# chain + key-level WAL compaction): history >= 10x live state, leader
# crash-recovery AND fresh-replica bootstrap each >= 5x faster than
# full-history replay and within 2x of a fresh-full-checkpoint
# restore, exact view parity everywhere, zero acked-write loss, and a
# bounded on-disk footprint after the final compaction pass.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_COMPACT=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py --json-out /tmp/_t1_compact.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_compact.json"))
assert r["parity_max_abs_diff"] == 0, r
assert r["zero_acked_loss"], r
assert r["history_ratio_ok"], r
assert r["recover_speedup_ok"], r
assert r["bootstrap_speedup_ok"], r
assert r["recover_near_floor_ok"], r
assert r["bootstrap_near_floor_ok"], r
assert r["footprint_bounded_ok"], r
assert r["chain_saves"] >= 1 and r["compact_folds"] >= 1, r
print(f"TIER1 compact smoke: history {r['history_ratio']}x state — "
      f"recover {r['recover_speedup_x']}x, bootstrap "
      f"{r['bootstrap_speedup_x']}x vs full replay (floor "
      f"{r['fresh_full_restore_s']}s); {r['acked_batches']} acked "
      f"batches, parity exact, zero loss; {r['compact_folds']} "
      f"fold(s), {r['chain_saves']} chain save(s), footprint "
      f"{r['wal_bounded_bytes']}/{r['wal_full_bytes']} bytes")
EOF
fi

# optional (RUN_BENCH=1): the tiles smoke — tiled maintenance: two
# identically-fed legs at state >= 8x the tile budget; the tiled leg
# must bound compaction and checkpoint writer/reader peaks under 2x
# budget, recover + bootstrap (through the per-file tile-unit
# protocol) with exact parity vs the monolithic leg, survive a kill
# at every per-tile crash seam with zero acked loss, answer top-k and
# point lookups identically to an untiled snapshot oracle, and keep
# small-state restore walls within 1.2x of untiled.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_TILES=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 590 python bench.py --json-out /tmp/_t1_tiles.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_tiles.json"))
assert r["schema"] == "reflow.bench/1" and r["mode"] == "tiles", r
assert r["legs_parity_max_abs_diff"] == 0, r
assert r["zero_acked_loss"], r
assert r["state_over_budget_x"] >= 8, r
assert 0 < r["compact_peak_tile_bytes"] <= 2 * r["tile_bytes"], r
assert 0 < r["ckpt_writer_peak_bytes"] <= 2 * r["tile_bytes"], r
assert 0 < r["ckpt_reader_peak_bytes"] <= 2 * r["tile_bytes"], r
assert r["ckpt_tile_count"] >= 4, r
assert r["tile_bootstraps"] >= 1 and r["tile_units_shipped"] > 0, r
assert r["topk_parity_ok"], r
assert len(r["crash_seams_survived"]) == 4, r
assert r["restore_wall_ok"] and r["bootstrap_wall_ok"], r
print(f"TIER1 tiles smoke: state {r['state_over_budget_x']}x budget — "
      f"compact peak {r['compact_peak_tile_bytes']}B, ckpt peaks "
      f"{r['ckpt_writer_peak_bytes']}/{r['ckpt_reader_peak_bytes']}B "
      f"(budget {r['tile_bytes']}B), {r['ckpt_tile_count']} tiles, "
      f"{r['tile_units_shipped']} unit(s) shipped, "
      f"{len(r['crash_seams_survived'])} seam(s) survived, walls "
      f"{r['restore_wall_ratio_x']}x/{r['bootstrap_wall_ratio_x']}x, "
      f"parity exact, zero loss")
EOF
fi

# optional (RUN_BENCH=1): the fleetobs smoke — the fleet telemetry
# plane on the replicated TCP topology: aggregator horizons must EQUAL
# ground truth at quiesce, at least one post-heal causal chain must
# span ship_segment->net_send->replica_replay (re-checked through
# trace_inspect --require-chain), the aggregator must keep serving
# stale-marked through a telemetry-link partition and recover, the
# saved fleet snapshot must round-trip through fleet_inspect as
# reflow.fleet/1, and every bench JSON this run produced must carry
# the reflow.bench/1 stamp (fleet_inspect --bench-dir). The <3%
# overhead acceptance holds on an uncontended host; shared CI cores
# make wall ratios noise, so the smoke takes a generous sanity ceiling
# and prints the measured number.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_FLEETOBS=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    REFLOW_TRACE_OUT=/tmp/_t1_fleet_trace.json \
    timeout -k 10 590 python bench.py --json-out /tmp/_t1_fleetobs.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_fleetobs.json"))
assert r["schema"] == "reflow.bench/1" and r["mode"] == "fleetobs", r
assert r["lag_spread_agg"] == r["lag_spread_truth"], r
assert r["lag_after_quiesce_ticks"] == 0, r
assert r["post_heal_required_chains"] >= 1, r
assert r["stale_during_partition"] == ["r0"], r
assert r["telemetry_partition_recovered"], r
assert r["fleet_nodes"] == r["replicas"] + 1, r
assert r["fleetobs_overhead_frac"] < 0.5, r
print(f"TIER1 fleetobs smoke: {r['fleet_nodes']} nodes, lag spread "
      f"{r['lag_spread_agg']} == truth, "
      f"{r['post_heal_required_chains']} post-heal causal chain(s), "
      f"served stale-marked through telemetry partition "
      f"({r['telemetry_dropped_r0']} dropped), overhead "
      f"{100 * r['fleetobs_overhead_frac']:.2f}%")
EOF
  python tools/trace_inspect.py /tmp/_t1_fleet_trace.json \
    --require-chain ship_segment,net_send,replica_replay > /dev/null \
    || { echo "TIER1: fleetobs require-chain failed"; rc=3; }
  python tools/fleet_inspect.py /tmp/reflow_fleet_snapshot.json --json \
    > /tmp/_t1_fleet_snap.json \
    || { echo "TIER1: fleet_inspect snapshot failed"; rc=3; }
  python - <<'EOF' || rc=3
import json
s = json.load(open("/tmp/_t1_fleet_snap.json"))
assert s["schema"] == "reflow.fleet/1", s
assert s["gauges"]["nodes_total"] >= 4 and not s["alerts"], s
d = json.load(__import__("os").popen(
    "python tools/fleet_inspect.py --bench-dir /tmp --json"))
assert d["schema"] == "reflow.fleet_benchdir/1", d
assert any(e["mode"] == "fleetobs" for e in d["benches"]), d
print(f"TIER1 fleetobs consumers: fleet/1 snapshot ok "
      f"({s['gauges']['nodes_total']} nodes, 0 alerts), bench dir "
      f"{d['stamped']} stamped / {d['unstamped']} pre-stamp")
EOF
fi

# optional (RUN_BENCH=1): the multiproc smoke — the whole control
# plane as real OS processes (leader + replicas + remote producers
# over the ingestion RPC), a kill -9 storm over every replica
# (respawn, recover over the mirrored WAL, rejoin through the
# cross-process horizon barrier) and then the leader (cross-process
# promotion; producers retarget and resubmit through the hello dedup
# handshake): zero acked-write loss vs a deterministic refold oracle,
# exact survivor parity at the promoted leader's horizon, empty
# in-doubt set on every producer, every kill accounted for. Children
# are reaped with deadlines — a wedged child fails the smoke instead
# of hanging it.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_MULTIPROC=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 590 python bench.py --json-out /tmp/_t1_multiproc.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_multiproc.json"))
assert r["acked_loss_max_abs_diff"] == 0, r
assert r["parity_max_abs_diff"] == 0, r
assert r["epoch"] == 1, r
assert r["fleet_nodes_seen"], r
assert r["reconnects_total"] >= r["producers"], r
assert r["resubmits_total"] >= 1, r
assert r["kills"] == r["replicas"] + 1, r
assert r["respawns"] == r["replicas"], r
print(f"TIER1 multiproc smoke: {r['replicas']} replica + "
      f"{r['producers']} producer processes — {r['kills']} kill -9s, "
      f"{r['respawns']} respawns, {r['winner']} promoted to epoch "
      f"{r['epoch']} in {r['promotion_s']}s; {r['acked_batches']} "
      f"acked batches, zero loss, survivor parity exact at tick "
      f"{r['leader_tick']}; {r['reconnects_total']} reconnect(s), "
      f"{r['resubmits_total']} resubmit(s), {r['deduped_total']} "
      f"deduped")
EOF
fi

# optional (RUN_BENCH=1): the subs smoke — reactive reads: one
# replica's SubscriptionHub fanning per-window deltas to simulated
# subscribers (plus real wire subscribers through a mid-run
# partition + heal of their endpoint) under sustained 16-producer
# writes: exact push-vs-pull parity at equal horizons, zero gaps and
# zero duplicate applies on resume, and the write path's admission
# p99 within 2x the no-subscriber baseline (with the bench's
# documented absolute floor so shared CI cores can't turn scheduler
# jitter into a spurious fail).
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_SUBS=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py --json-out /tmp/_t1_subs.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_subs.json"))
assert r["schema"] == "reflow.bench/1" and r["mode"] == "subs", r
assert r["subs"]["parity_max_abs_diff"] == 0, r
assert r["write_p99_bounded"], r
assert r["subs"]["active_subs"] >= r["subscribers"], r
assert r["subs"]["wire_reconnects"] >= r["wire_subscribers"], r
print(f"TIER1 subs smoke: {r['subscribers']} subscribers, "
      f"{r['subs']['fanout_rows_per_s']} fan-out rows/s, write p99 "
      f"{r['write_p99_overhead_x']}x baseline (bounded), parity "
      f"exact, {r['subs']['wire_reconnects']} wire reconnect(s) "
      f"gap-free")
EOF
fi

# optional (RUN_BENCH=1): the e2etrace smoke — follow-the-write across
# the whole process fleet: sampled writes must stitch one causal chain
# producer_submit -> rpc_admit -> admission -> wal_append ->
# ship_segment -> net_send -> replica_replay -> sub_fanout ->
# sub_deliver through a kill -9 of a replica AND the leader (with a
# post-promotion chain in the new epoch), the ack->push freshness
# decomposition must tile end-to-end latency within 10%, unstamped
# wire messages must stay byte-identical to the legacy encoding, and
# every killed child's flight recording must be recoverable from its
# disk corner. The kept traces are re-checked through trace_inspect
# --require-chain, same as a human post-mortem would.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_E2ETRACE=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 590 python bench.py --json-out /tmp/_t1_e2etrace.json \
    > /dev/null || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_e2etrace.json"))
assert r["schema"] == "reflow.bench/1" and r["mode"] == "e2etrace", r
assert r["wire_compat_identical"], r
assert r["full_chains"] >= 1, r
assert r["required_chains"] >= 1, r
assert r["freshness_max_dev_frac"] <= 0.10, r
assert r["post_promotion_submits"] >= 1, r
assert "leader" in r["flight_nodes"], r
print(f"TIER1 e2etrace smoke: {r['full_chains']} full chain(s) across "
      f"{r['trace_files_merged']} processes, freshness e2e p50 "
      f"{r['freshness_e2e_p50_us']:.0f}us (tiling dev "
      f"{100 * r['freshness_max_dev_frac']:.2f}%), "
      f"{r['post_promotion_submits']} post-promotion sampled "
      f"submit(s), flight recordings from "
      f"{len(r['flight_nodes'])} node(s)")
EOF
  python tools/trace_inspect.py /tmp/reflow_e2etrace_traces/*-trace.json \
    --require-chain producer_submit,rpc_admit,admission,wal_append,ship_segment,net_send,replica_replay,sub_fanout,sub_deliver \
    > /dev/null \
    || { echo "TIER1: e2etrace require-chain failed"; rc=3; }
fi
exit $rc
