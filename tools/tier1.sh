#!/usr/bin/env bash
# The tier-1 verify gate, verbatim from ROADMAP.md — CI and humans run
# the IDENTICAL command (CPU-forced jax, `slow`-marked tests excluded,
# collection errors tolerated so one broken module can't hide the rest).
# Prints DOTS_PASSED=<n> (count of passing-test dots) and exits with
# pytest's status.
set -o pipefail
cd "$(dirname "$0")/.."

# fast-fail static pass BEFORE the 15-minute pytest budget: a syntax
# error or obvious undefined name should cost seconds, not a timeout.
# pyflakes is optional in the image; compileall is stdlib.
python -m compileall -q reflow_tpu tests tools bench.py bench_configs.py \
  || { echo "TIER1: compileall failed"; exit 2; }
if python -c "import pyflakes" 2>/dev/null; then
  python -m pyflakes reflow_tpu bench.py bench_configs.py \
    || { echo "TIER1: pyflakes failed"; exit 2; }
fi

rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)

# optional (RUN_BENCH=1): the serve-mode smoke — sustained ingestion
# throughput must coalesce (>1 micro-batch/tick at 16 producers) with
# zero forced syncs; ~seconds on CPU at smoke scale.
if [ "${RUN_BENCH:-0}" = "1" ] && [ $rc -eq 0 ]; then
  REFLOW_BENCH_SERVE=1 REFLOW_BENCH_SMOKE=1 JAX_PLATFORMS=cpu \
    timeout -k 10 300 python bench.py > /tmp/_t1_serve.json || rc=3
  python - <<'EOF' || rc=3
import json
r = json.load(open("/tmp/_t1_serve.json"))
assert r["coalesce_gt_1_at_16p"], r
assert r["zero_forced_syncs"], r
print(f"TIER1 serve smoke: {r['serve_16p_rows_per_s']} rows/s @16p, "
      f"coalesce {r['serve_16p_coalesce_factor']}x")
EOF
fi
exit $rc
