"""Audit every compiled program for multi-element HLO constants.

Shim: the audit now lives in ``reflow_tpu/analysis/constants.py`` and
runs as reflow-lint's opt-in slow pass (``python tools/reflow_lint.py
--hlo``). This entry point keeps the historical CLI working.

Usage: python tools/audit_constants.py [workload ...]
Exit code 1 if any multi-element constant is found.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from reflow_tpu.analysis.constants import WORKLOADS, audit  # noqa: E402


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    targets = sys.argv[1:] or list(WORKLOADS)
    fail = False
    for w in targets:
        bad = audit(w, repo)
        status = ("CLEAN" if not bad
                  else f"{len(bad)} multi-element constants")
        print(f"{w}: {status}")
        for item in bad:
            print("  " + "  ".join(str(x) for x in item))
            fail = True
    return 1 if fail else 0


if __name__ == "__main__":
    sys.exit(main())
