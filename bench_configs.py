"""Per-config benchmark records for BASELINE.md configs 1, 2, 4, 5.

Config 3 (incremental PageRank) is the headline and lives in bench.py;
this module measures the remaining four and emits one JSON record each on
stderr (via the passed ``log``), so the driver's BENCH tail carries all
five per-config records while stdout keeps the single headline line.

Each config is wrapped so a failure records an error line instead of
killing the whole bench run.
"""

from __future__ import annotations

import json
import time

import numpy as np

from reflow_tpu.utils.config import (env_flag, env_float, env_int, env_str)


def _record(log, name: str, rec: dict) -> None:
    rec = {"config": name, **rec}
    log(json.dumps(rec))


def _sync_read(executor) -> None:
    """Force TRUE device completion with one host readback.

    ``jax.block_until_ready`` does NOT wait for remote completion over a
    tunnel-attached device (it resolves the local handle only), so walls
    "synced" with it are dispatch walls — VERDICT r2 weak #4 in disguise.
    The only reliable barrier is a device->host read of a value the last
    program produced; the device stream is in-order, so reading ONE small
    leaf of the final state barriers everything dispatched before it.

    Caveat that shapes this whole harness: the FIRST such read flips the
    tunnel runtime into a degraded synchronous mode for the rest of the
    process (~70-150ms per subsequent sync, chained dispatches ~66ms).
    Measure in pipelined windows (``_stream_window``) and read once at
    the end; run each config in its own subprocess (bench.py)."""
    states = getattr(executor, "states", None)
    if not states:
        return
    import jax

    leaves = [x for st in states.values()
              for x in jax.tree.leaves(st) if hasattr(x, "dtype")]
    if leaves:
        np.asarray(min(leaves, key=lambda x: getattr(x, "size", 1 << 60)))


def _timed_tick(sched, **kw):
    """One tick measured to device completion via ``_sync_read`` (the CPU
    oracle is synchronous by construction and its states are giant host
    Counters — pytree traversal there costs hundreds of ms and would
    inflate the baseline's walls, so only device executors barrier)."""
    t0 = time.perf_counter()
    r = sched.tick(**kw)
    if getattr(sched.executor, "name", "") != "cpu":
        _sync_read(sched.executor)
    return time.perf_counter() - t0, r


def _settle(seconds: float, log=None, why: str = "") -> None:
    """Let already-dispatched device work drain WITHOUT a readback.

    A barrier before a measurement window would be a device->host read —
    and the first read permanently degrades the tunnel (see _sync_read).
    Sleeping keeps the runtime in pipelined mode while the in-order
    device stream finishes warmup/preload work, so the window that
    follows measures only its own ticks. Generous durations: undershoot
    leaks residue INTO the window (inflating it — any error is
    conservative for speedup claims)."""
    if log is not None:
        log(f"settle {seconds:.0f}s ({why})")
    time.sleep(seconds)


def _median_window(run_once, log, tag: str, n: int = 3):
    """Run ``n`` measurement windows, return ``(wall, dispatch_wall,
    delta_ops)`` of the MEDIAN-throughput window.

    Shared outlier protocol: the tunnel shows rare far-outlier windows
    (recorded spreads up to 30x for identical programs), and window 0's
    closing barrier flips the runtime into its post-readback mode where
    chained windows run at true device speed — the median lands on a
    genuine completion-time wall either way. The returned dispatch wall
    is WINDOW 0's: only there is dispatch pipelined (later windows block
    to completion, dwall ~= wall), so its smallness is the evidence the
    measurement was device-bound, not host-bound.

    ``run_once() -> (wall_s, dispatch_wall_s, delta_ops)``. Returns
    ``(median_wall, window0_dispatch_wall, median_delta_ops, windows)``
    with ``windows`` the full per-window list for diagnostics.
    """
    windows = []
    for ix in range(n):
        wall, dwall, dops = run_once()
        windows.append((wall, dwall, dops))
        log(f"{tag} window {ix}: {wall:.2f}s "
            f"({dops / wall:,.0f} delta-ops/s)")
    ordered = sorted(windows, key=lambda w: w[2] / w[0])
    wall, _, dops = ordered[len(ordered) // 2]
    return wall, windows[0][1], dops, windows


def _stream_window(sched, feed, n: int):
    """Pipelined measurement window: dispatch ``n`` streaming ticks
    back-to-back with ZERO host readbacks (the tunnel stays in pipelined
    mode, the device runs the ticks shoulder to shoulder), then force
    completion with one readback. Returns ``(wall, dispatch_wall,
    results)`` — ``wall`` covers dispatch + all device compute;
    ``dispatch_wall`` shows the host enqueue cost (its smallness is the
    evidence the window was device-bound). Error checks and TickResult
    scalar conversion run after the clock stops."""
    t0 = time.perf_counter()
    results = []
    for i in range(n):
        feed(i)
        results.append(sched.tick(sync=False))
    dispatch_wall = time.perf_counter() - t0
    _sync_read(sched.executor)
    wall = time.perf_counter() - t0
    sched.executor.check_errors()
    for r in results:
        r.block()
    return wall, dispatch_wall, results


def _pad_batch(batch, rows: int):
    """Pad a host DeltaBatch to a fixed row count with weight-0 rows so
    every edit tick hits ONE capacity bucket (VERDICT r2 weak #5: batches
    wandering across buckets kept recompiling in steady state)."""
    from reflow_tpu.delta import DeltaBatch

    n = len(batch)
    if n >= rows:
        return batch
    pad = rows - n
    vals = np.zeros((pad,) + batch.values.shape[1:], batch.values.dtype)
    return DeltaBatch.concat([batch, DeltaBatch(
        np.zeros(pad, np.int64), vals, np.zeros(pad, np.int64))])


def control_scenario(smoke: bool) -> dict:
    """Step-load knobs for bench.py's ``REFLOW_BENCH_CONTROL`` mode
    (hot-tenant surge + pump-crash storm under a live ControlPlane).

    One place for the scenario's shape so the bench and the tier-1
    smoke assert against the same numbers. The budget is sized so the
    hot tenant genuinely saturates its byte ceiling (wordcount
    micro-batches are tiny); the control interval is fast enough that
    recovery-in-intervals is measured in tens of milliseconds, not
    seconds. ``recovery_slack_ticks`` pads the analytic recovery bound
    (ladder rungs x recover_intervals) with the ticks the pool needs to
    drain in-flight bytes after the surge stops."""
    return {
        "budget_bytes": 8 << 10,
        "pump_threads": 2,
        "interval_s": 0.005,
        # hot tenant's SLO: occupancy of its ceiling, 2-interval breach
        # confirm, 2-interval per-rung recovery hysteresis
        "occupancy_slo": 0.6,
        "breach_intervals": 2,
        "recover_intervals": 2,
        "hammers": 3,
        "quiet_batches": 60 if smoke else 200,
        # quiet tenant's admission p99 bound during the surge (same
        # bound phase C of the tier bench enforces without a controller)
        "quiet_p99_bound_s": 0.05,
        "recovery_slack_ticks": 12,
        # crash-storm breaker knobs (fast cooldowns: the bench proves
        # the open -> half-open -> closed arc, not production pacing)
        "max_crashes": 3,
        "crash_window_s": 30.0,
        "respawn_backoff_s": 0.0,
        "respawn_backoff_max_s": 0.01,
        "breaker_cooldown_s": 0.02,
        "breaker_cooldown_max_s": 0.1,
        "probe_intervals": 2,
    }


def _guard(log, name: str):
    def deco(fn):
        def wrapped(*a, **k):
            try:
                return fn(*a, **k)
            except Exception as e:  # noqa: BLE001 - bench must keep going
                _record(log, name, {"error": f"{type(e).__name__}: {e}"})
        return wrapped
    return deco


# -- config 1: incremental word-count, CPU executor ------------------------

def cfg1_wordcount(smoke: bool, log) -> None:
    @_guard(log, "1_wordcount")
    def run():
        from reflow_tpu.scheduler import DirtyScheduler
        from reflow_tpu.workloads import wordcount

        n_lines = 2_000 if smoke else 100_000
        per_tick = 500 if smoke else 10_000
        rng = np.random.default_rng(0)
        vocab_words = [f"w{i}" for i in range(5_000)]
        lines = [" ".join(rng.choice(vocab_words,
                                     size=rng.integers(5, 15)))
                 for _ in range(n_lines)]

        g, src, sink = wordcount.build_graph()
        sched = DirtyScheduler(g)  # CpuExecutor: the default path
        walls, dops = [], []
        for i in range(0, n_lines, per_tick):
            sched.push(src, wordcount.ingest_lines(lines[i:i + per_tick]))
            r = sched.tick()
            walls.append(r.wall_s)
            dops.append(r.delta_ops)
        # one retraction tick (incremental un-count)
        sched.push(src, wordcount.ingest_lines(lines[:per_tick], weight=-1))
        r = sched.tick()
        walls.append(r.wall_s)
        dops.append(r.delta_ops)
        _record(log, "1_wordcount", {
            "executor": "cpu",
            "lines": n_lines,
            "delta_ops_per_s": round(sum(dops) / sum(walls)),
            "ticks": len(walls),
        })
    run()


# -- config 2: streaming TF-IDF, CPU + TPU ---------------------------------

def cfg2_tfidf(smoke: bool, log) -> None:
    from reflow_tpu.executors import get_executor
    from reflow_tpu.scheduler import DirtyScheduler
    from reflow_tpu.workloads import tfidf

    n_docs = 64 if smoke else 4_096
    # 2^20-term vocabulary (a real Wikipedia-scale vocab is ~10^6; the
    # radix-split presence path is exact to 2^24 — workloads/tfidf.py)
    n_terms = 1 << (10 if smoke else 20)
    # pair capacity covers the full run: initial corpus plus the per-edit
    # AND micro-batched phases at 1 warm + 3 measured windows each (every
    # edit interns ~45 fresh (doc,term) pairs; real scale ~540k total)
    n_pairs = 1 << (15 if smoke else 20)
    edits = 32 if smoke else 512
    vocab = 1_000 if smoke else 250_000  # drawn words (ids intern densely)
    # np array, not list: rng.choice over a list re-converts all 250k
    # strings per call (~20ms x thousands of edits)
    words = np.array([f"t{i}" for i in range(vocab)])

    for ex_name in ("cpu", "tpu"):
        @_guard(log, f"2_tfidf_{ex_name}")
        def run(ex_name=ex_name):
            rng = np.random.default_rng(1)
            corpus = tfidf.Corpus(n_pairs, n_terms)
            tg = tfidf.build_graph(n_pairs, n_terms, n_docs)
            sched = DirtyScheduler(tg.graph, get_executor(ex_name))

            def text():
                return " ".join(rng.choice(words, size=rng.integers(20, 60)))

            # initial corpus load (streaming on the device path: a sync
            # tick's error check reads a device scalar, and the FIRST
            # readback permanently degrades the tunnel — see _sync_read)
            batches = [corpus.edit(d, text()) for d in range(n_docs // 2)]
            from reflow_tpu.delta import DeltaBatch
            sched.push(tg.tokens, DeltaBatch.concat(batches))
            sched.tick(sync=ex_name == "cpu")
            # device path: every edit batch is padded to ONE fixed
            # capacity bucket so steady state compiles exactly one churn
            # program. The CPU oracle pays per-row cost for pad rows, so
            # it gets the raw batches; pad rows are excluded from BOTH
            # executors' delta-ops numerators (they are no-ops)
            edit_rows = 256 if ex_name != "cpu" else 0

            def _push_edit(batch):
                pad = max(0, edit_rows - len(batch))
                sched.push(tg.tokens, _pad_batch(batch, edit_rows)
                           if edit_rows else batch)
                return pad

            if ex_name == "cpu":
                _push_edit(corpus.edit(0, text()))  # warm the churn shape
                _timed_tick(sched)
                walls, dops = [], []
                for i in range(edits):
                    d = int(rng.integers(0, n_docs))
                    pad = _push_edit(corpus.edit(d, text()))
                    wall, r = _timed_tick(sched)
                    walls.append(wall)
                    dops.append(r.delta_ops - pad)
                _record(log, f"2_tfidf_{ex_name}", {
                    "executor": ex_name,
                    "docs": n_docs, "terms": n_terms,
                    "edits": edits,
                    "delta_ops_per_s": round(sum(dops) / sum(walls)),
                    "tick_ms_median": round(1e3 * float(np.median(walls)), 2),
                })
            else:
                # device path: ALL edits of a window scan-fuse into ONE
                # device execution (tick_many on the loop-free graph),
                # amortizing the tunnel's per-execution overhead across
                # the whole window; zero readbacks before the barrier
                pads = []

                def make_feed():
                    d = int(rng.integers(0, n_docs))
                    b = corpus.edit(d, text())
                    pads.append(max(0, edit_rows - len(b)))
                    return {tg.tokens: _pad_batch(b, edit_rows)}

                sched.tick_many([make_feed() for _ in range(edits)])  # warm
                pads.clear()
                _settle(0 if smoke else 15, log,
                        "drain tfidf initial load + warm window")
                def run_edit_window():
                    feeds = [make_feed() for _ in range(edits)]
                    t0 = time.perf_counter()
                    agg = sched.tick_many(feeds)
                    dwall = time.perf_counter() - t0
                    _sync_read(sched.executor)
                    wall = time.perf_counter() - t0
                    sched.executor.check_errors()
                    agg.block()
                    dops = agg.delta_ops - sum(pads)
                    pads.clear()
                    return wall, dwall, dops

                wall, dwall, dops, _ = _median_window(
                    run_edit_window, log, "2_tfidf edit")
                _record(log, f"2_tfidf_{ex_name}", {
                    "executor": ex_name,
                    "docs": n_docs, "terms": n_terms,
                    "edits": edits,
                    "delta_ops_per_s": round(dops / wall),
                    "tick_ms_amortized": round(1e3 * wall / edits, 2),
                    "dispatch_ms_total": round(1e3 * dwall, 1),
                })

                # micro-batched streaming: a realistic ingestion buffer
                # groups edits per tick — a 256-row single edit cannot
                # fill the chip, a few-thousand-row micro-batch can
                group = 8 if smoke else 64
                ticks2 = 4 if smoke else 32
                # one bucket above any group's worst case (~80 rows/edit:
                # retract+insert per touched term), so every window tick
                # pads to ONE capacity and the measured window can never
                # compile a fresh scan program mid-measurement
                cap2 = 1024 if smoke else 8192
                pads2 = []

                def make_group():
                    bs = []
                    for _ in range(group):
                        d = int(rng.integers(0, n_docs))
                        bs.append(corpus.edit(d, text()))
                    b = DeltaBatch.concat(bs)
                    pads2.append(max(0, cap2 - len(b)))
                    return {tg.tokens: _pad_batch(b, cap2)}

                sched.tick_many([make_group() for _ in range(ticks2)])
                pads2.clear()
                _settle(0 if smoke else 10, log, "drain batched warm")

                def run_batched_window():
                    feeds2 = [make_group() for _ in range(ticks2)]
                    t0 = time.perf_counter()
                    agg2 = sched.tick_many(feeds2)
                    dwall2 = time.perf_counter() - t0
                    _sync_read(sched.executor)
                    wall2 = time.perf_counter() - t0
                    sched.executor.check_errors()
                    agg2.block()
                    dops2 = agg2.delta_ops - sum(pads2)
                    pads2.clear()
                    return wall2, dwall2, dops2

                wall2, _, dops2, _ = _median_window(
                    run_batched_window, log, "2_tfidf batched")
                _record(log, "2_tfidf_tpu_batched", {
                    "executor": ex_name,
                    "docs": n_docs, "terms": n_terms,
                    "edits_per_tick": group, "ticks": ticks2,
                    "delta_ops_per_s": round(dops2 / wall2),
                    "edits_per_s": round(group * ticks2 / wall2, 1),
                    "tick_ms_amortized": round(1e3 * wall2 / ticks2, 2),
                })
        run()


# -- config 4: k-NN re-index on 1Mx768 embedding deltas, TPU ---------------

def cfg4_knn(smoke: bool, log) -> None:
    @_guard(log, "4_knn")
    def run():
        from reflow_tpu.executors import get_executor
        from reflow_tpu.scheduler import DirtyScheduler
        from reflow_tpu.workloads import knn

        import os

        if smoke:
            Q, D, dim, k, chunk = 64, 4096, 64, 8, 1024
            per_tick, preload = 256, 1024
        else:
            Q, D, dim, k, chunk = 256, 1 << 20, 768, 16, 8192
            per_tick = 8192
            # the BASELINE scale is a 1Mx768 corpus; the preload is
            # env-tunable but clamped to leave headroom for every
            # measured insert tick (absorb + 3 windows x 6 x per_tick):
            # an id wrap during measurement would turn inserts into
            # in-place updates (which rescan) and also break the
            # wrap-aware live-row accounting at the record step
            cap_preload = (1 << 20) - 24 * 8192
            preload = min(env_int("REFLOW_BENCH_KNN_PRELOAD", cap_preload), cap_preload)

        # int8 quantized corpus ingest (VERDICT r4 #3a): round(unit*127)
        # on the wire — 1 byte/dim, HALF the bf16 wire+HBM cost that was
        # the measured binding constraint of this config — dequantized to
        # bf16 at score time on chip (kernels.topk.score_form; recall
        # bound tested in tests/test_knn.py). Queries stay bf16 (their
        # upload is negligible). REFLOW_BENCH_KNN_DTYPE=bf16 restores
        # the previous wire format for A/B runs.
        import jax.numpy as jnp
        wire = env_str("REFLOW_BENCH_KNN_DTYPE", "int8")
        doc_dtype = jnp.int8 if wire == "int8" else jnp.bfloat16
        kg = knn.build_graph(Q, D, dim, k, scan_chunk=chunk,
                             dtype=jnp.bfloat16, doc_dtype=doc_dtype,
                             precision="default")
        # generator-only here: the corpus preload below is device-made, so
        # store.vecs mirrors ONLY the measured host-boundary inserts (never
        # use store.reference_topk / len(store.vecs) in this config)
        store = knn.EmbeddingStore.create(dim, seed=3)
        sched = DirtyScheduler(kg.graph, get_executor("tpu"))
        qvecs = store._random(Q)
        from reflow_tpu.delta import DeltaBatch
        sched.push(kg.queries, DeltaBatch(
            np.arange(Q, dtype=np.int64), qvecs, np.ones(Q, np.int64)))
        next_id = 0

        def insert(n):
            nonlocal next_id
            # wrap into the corpus key space: once the id range is
            # exhausted, inserts become embedding UPDATES of existing
            # ids (the steady re-index regime) instead of out-of-range
            # keys the device would silently drop
            ids = np.arange(next_id, next_id + n) % D
            next_id += n
            return store.insert_batch(ids, quantize=(wire == "int8"))

        # corpus preload GENERATED ON DEVICE: the preload is bench
        # fixture setup (the measured flow is the insert windows below,
        # which still cross the host boundary as real ingestion), and
        # synthesizing it with the on-chip RNG replaces a ~1.3GB
        # host->device upload — measured 40+ min on a congested tunnel —
        # with a dozen device executions. Zero readbacks, so the tunnel
        # stays in pipelined mode (see _sync_read)
        import jax

        from reflow_tpu.executors.device_delta import DeviceDelta

        # smoke keeps the chunk small so the device-generated preload
        # path runs under CI too, not just on 40-minute real-chip runs
        big = 512 if smoke else 1 << 16

        @jax.jit
        def gen_chunk(seed, base):
            kk = jax.random.fold_in(jax.random.PRNGKey(3), seed)
            vals = jax.random.normal(kk, (big, dim), jnp.float32)
            keys = (base + jnp.arange(big, dtype=jnp.int32)) % D
            if doc_dtype == jnp.int8:
                # device-side form of workloads.knn.quantize_int8
                nrm = jnp.sqrt(jnp.sum(vals * vals, axis=1, keepdims=True))
                unit = vals / jnp.maximum(nrm, 1e-30)
                rows = jnp.clip(jnp.round(unit * 127.0), -127, 127
                                ).astype(jnp.int8)
            else:
                rows = jnp.asarray(vals, doc_dtype)
            return DeviceDelta(keys, rows, jnp.ones((big,), jnp.int32))

        def retract(ids):
            # device knn retraction clears the id's live bit and never
            # consults the value (lowerings._fold_vectors), so zero rows
            # stand in for the device-generated preload vectors
            return DeltaBatch(np.asarray(ids, np.int64),
                              np.zeros((len(ids), dim), np.float32),
                              -np.ones(len(ids), np.int64))

        t0 = time.perf_counter()
        chunk_ix = 0
        while next_id + big <= preload:
            sched.push(kg.docs, gen_chunk(np.int32(chunk_ix),
                                          np.int32(next_id % D)))
            sched.tick(sync=False)
            next_id += big
            chunk_ix += 1
        preload_s = time.perf_counter() - t0   # dispatch wall (pipelined)
        sched.push(kg.docs, insert(per_tick))
        sched.tick(sync=False)
        sched.push(kg.docs, retract(np.arange(per_tick // 8)))
        sched.tick(sync=False)
        _settle(0 if smoke else env_float("REFLOW_BENCH_KNN_SETTLE", 60), log,
            "drain the corpus preload + absorb ticks before the window")

        # insert-heavy re-index flow (median-of-3 windows, _stream_window).
        # NOT a macro-tick: fusing the 6 ticks into one scan execution was
        # measured SLOWER here (10-12s vs ~4.7s per window) — the tunnel
        # runtime timeslices single long executions (the bench.py NOTE),
        # and with 12MB of upload per tick the scan turns the window into
        # one giant stretched execution. Per-tick streaming keeps the
        # uploads pipelined against compute.
        def run_insert_window():
            wall, dwall, results = _stream_window(
                sched, lambda i: sched.push(kg.docs, insert(per_tick)), 6)
            return wall, dwall, sum(r.delta_ops for r in results)

        wall, dwall, dops, _ = _median_window(
            run_insert_window, log, "4_knn insert")

        # one retraction tick: triggers the chunked full-corpus rescan.
        # Measured AFTER the window's barrier, so the wall carries one
        # degraded-tunnel sync (~0.1s) on top of device time — i.e. the
        # reported wall is conservative (an overestimate), never an
        # enqueue time (VERDICT r2 weak #4)
        retract_ids = np.arange(per_tick // 8, per_tick // 4)
        sched.push(kg.docs, retract(retract_ids))
        rescan_wall, r = _timed_tick(sched)

        # the rescan is one [Q, D_cap] x [D_cap, dim] similarity matmul:
        # report achieved TFLOP/s so the wall defends itself
        rescan_gflop = 2.0 * Q * D * dim / 1e9
        # live rows, wrap-aware: ids retracted in the absorb tick
        # (0..per_tick//8) are re-enlivened by wrapped inserts once
        # next_id passes D + id; the post-window retract never is
        re_ins = min(max(next_id - D, 0), per_tick // 8)
        live_rows = (min(next_id, D) - (per_tick // 8 - re_ins)
                     - per_tick // 8)
        wire_bytes = 1 if doc_dtype == jnp.int8 else 2
        _record(log, "4_knn", {
            "executor": "tpu",
            "queries": Q,
            "corpus": live_rows,
            "corpus_capacity": D,
            "dim": dim, "k": k,
            "embed_wire_dtype": wire,
            "upload_mb_per_tick": round(
                per_tick * dim * wire_bytes / 1e6, 2),
            "preload_dispatch_s": round(preload_s, 1),
            "delta_ops_per_s": round(dops / wall),
            "insert_tick_ms_amortized": round(1e3 * wall / 6, 1),
            "dispatch_ms_total": round(1e3 * dwall, 1),
            "rescan_tick_ms": round(1e3 * rescan_wall, 1),
            "rescan_achieved_tflops": round(
                rescan_gflop / max(rescan_wall, 1e-9) / 1e3, 1),
        })
    run()


# -- config 5: image-embed ETL (ViT feature extract), sharded --------------

def cfg5_image_embed(smoke: bool, log) -> None:
    @_guard(log, "5_image_embed")
    def run():
        import jax

        from reflow_tpu.models import VIT_B_16, VIT_TINY, init_vit
        from reflow_tpu.parallel import make_mesh
        from reflow_tpu.parallel.shard import ShardedTpuExecutor
        from reflow_tpu.scheduler import DirtyScheduler
        from reflow_tpu.workloads import image_embed

        import os as _os

        cfg = VIT_TINY if smoke else VIT_B_16
        # 256-image batches (VERDICT r3 #3): a 16-image tick leaves the
        # chip ~99% idle and even 64 images paid mostly fixed overhead.
        # 256 uint8 images = ~38MB of upload per tick, which at the
        # tunnel's measured ~35-53MB/s is the binding constraint — the
        # record carries upload_mb_per_tick + mfu so the ceiling is
        # visible in the data (env-tunable for directly-attached chips)
        per_tick = 8 if smoke else env_int("REFLOW_BENCH_IMG_PER_TICK", 256)
        ticks = 2 if smoke else 4
        n_groups = 64
        n_images = 1 << 14
        params = init_vit(0, **cfg)
        params["_cfg"] = cfg

        # REFLOW_BENCH_MODEL_AXIS=m: tensor-parallel the ViT over an
        # m-way model axis (2-D delta x model mesh, VERDICT r4 #8) —
        # params shard 1/m per device; needs >= m local devices. The
        # single-chip tunnel default is the 1-D data mesh.
        m_tp = env_int("REFLOW_BENCH_MODEL_AXIS", 0)
        n_dev = len(jax.devices())
        if m_tp >= 2 and n_dev >= m_tp and n_dev % m_tp == 0:
            from reflow_tpu.parallel.mesh import make_model_mesh
            mesh = make_model_mesh(n_dev // m_tp, m_tp)
            ex = ShardedTpuExecutor(mesh, model_axis="model")
            ig = image_embed.build_graph(n_images, n_groups, params,
                                         model_axis="model")
        else:
            mesh = make_mesh()  # all local devices (1 on the real chip)
            ex = ShardedTpuExecutor(mesh)
            ig = image_embed.build_graph(n_images, n_groups, params)
        sched = DirtyScheduler(ig.graph, ex)
        embed_node = next(n for n in ig.graph.nodes if n.name == "embed")
        param_mb_dev = sum(
            s.data.nbytes for leaf in jax.tree.leaves(
                ex.states[embed_node.id]["params"])
            for s in leaf.addressable_shards[:1]) / 1e6
        stream = image_embed.ImageStream(params, seed=5)
        next_id = 0

        def insert(n):
            nonlocal next_id
            ids = np.arange(next_id, next_id + n)
            groups = ids % n_groups
            next_id += n
            return stream.insert(ids, groups)

        # macro-tick window: all K image ticks scan-fuse into ONE device
        # execution (the graph is sink-free and loop-free), amortizing
        # the tunnel's fixed per-execution overhead — the same shape as
        # config 2's micro-batched path. Absorption runs the SAME K as
        # the measured windows (the scan program's shape includes K) plus
        # one single-tick move shape, so nothing compiles mid-measurement
        sched.tick_many([{ig.images: insert(per_tick)} for _ in range(ticks)])
        sched.push(ig.images, stream.move(0, 1))
        sched.tick(sync=False)
        _settle(0 if smoke else 30, log,
                "drain the absorption window before measuring")

        def run_image_window():
            feeds = [{ig.images: insert(per_tick)} for _ in range(ticks)]
            t0 = time.perf_counter()
            agg = sched.tick_many(feeds)
            dwall = time.perf_counter() - t0
            _sync_read(sched.executor)
            wall = time.perf_counter() - t0
            sched.executor.check_errors()
            agg.block()
            return wall, dwall, agg.delta_ops

        wall, dwall, dops, _ = _median_window(
            run_image_window, log, "5_image_embed")

        # DEVICE-BOUND window (VERDICT r4 #3b): the same ingestion flow
        # with pixel batches GENERATED ON CHIP (the cfg4 preload trick),
        # so the record separates the model-compute ceiling from the
        # tunnel-upload ceiling — upload per tick drops from ~38MB to
        # the dispatch bytes of one seed scalar
        import jax.numpy as jnp
        from functools import partial

        from jax.sharding import NamedSharding, PartitionSpec as P

        from reflow_tpu.executors.device_delta import DeviceDelta

        flat = cfg["img"] * cfg["img"] * cfg["chans"]
        row_sh = NamedSharding(
            mesh, P(mesh.axis_names if len(mesh.axis_names) > 1
                    else mesh.axis_names[0]))

        @partial(jax.jit,
                 out_shardings=DeviceDelta(row_sh, row_sh, row_sh))
        def gen_imgs(seed, base):
            kk = jax.random.fold_in(jax.random.PRNGKey(11), seed)
            pix = jax.random.randint(kk, (per_tick, flat), 0, 256,
                                     jnp.int32).astype(jnp.uint8)
            ids = base + jnp.arange(per_tick, dtype=jnp.int32)
            grp = (ids % n_groups).astype(jnp.uint8)
            vals = jnp.concatenate([grp[:, None], pix], axis=1)
            return DeviceDelta(ids % n_images, vals,
                               jnp.ones((per_tick,), jnp.int32))

        dev_seed = 0

        def dev_tick():
            nonlocal dev_seed, next_id
            sched.push(ig.images, gen_imgs(np.int32(dev_seed),
                                           np.int32(next_id % n_images)))
            dev_seed += 1
            next_id += per_tick
            sched.tick(sync=False)

        dev_tick()                      # absorb the device-gen shape
        _sync_read(sched.executor)
        t0 = time.perf_counter()
        for _ in range(ticks):
            dev_tick()
        _sync_read(sched.executor)
        dev_wall = time.perf_counter() - t0
        sched.executor.check_errors()

        # a group move: retract/insert pair through the model. Post-window
        # wall carries one degraded-tunnel sync — conservative, never an
        # enqueue time. Group 2 (absorption already moved image 0 to 1):
        # a same-group move would cancel to a no-op tick
        sched.push(ig.images, stream.move(0, 2))
        move_wall, r = _timed_tick(sched)

        # achieved model FLOP/s + MFU (VERDICT r3 #3): images/s x the
        # model's matmul FLOPs per image (FMA=2 convention) against the
        # v5e's 197 TFLOP/s bf16 peak — alongside the per-tick upload
        # volume, so the record itself shows which wall binds
        from reflow_tpu.models.vit import vit_flops

        img_per_s = per_tick * ticks / wall
        flops = vit_flops(**cfg)
        peak = 197e12  # TPU v5e bf16 peak FLOP/s
        upload_mb = per_tick * cfg["img"] * cfg["img"] * cfg["chans"] / 1e6
        _record(log, "5_image_embed", {
            "executor": "sharded",
            "mesh_devices": len(mesh.devices.ravel()),
            "model_axis": m_tp if m_tp >= 2 else None,
            "param_mb_per_device": round(param_mb_dev, 1),
            "model": "vit_tiny" if smoke else "vit_b_16",
            "images_per_tick": per_tick,
            "delta_ops_per_s": round(dops / wall, 1),
            "images_per_s": round(img_per_s, 2),
            "model_gflop_per_image": round(flops / 1e9, 1),
            "achieved_tflops": round(img_per_s * flops / 1e12, 2),
            # aggregate mesh throughput against the AGGREGATE mesh peak
            # (ADVICE r4: dividing by one chip's peak inflated MFU by the
            # mesh size on multi-device meshes)
            "mfu_pct_vs_v5e_bf16_peak": round(
                100 * img_per_s * flops
                / (peak * len(mesh.devices.ravel())), 2),
            "upload_mb_per_tick": round(upload_mb, 1),
            "dispatch_ms_total": round(1e3 * dwall, 1),
            "move_tick_ms": round(1e3 * move_wall, 1),
            # tunnel factored out: on-chip-generated pixels, ~0MB upload
            "images_per_s_device_bound": round(
                per_tick * ticks / dev_wall, 2),
            "mfu_pct_device_bound": round(
                100 * (per_tick * ticks / dev_wall) * flops
                / (peak * len(mesh.devices.ravel())), 2),
        })
    run()
